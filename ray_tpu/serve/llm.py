"""LLM serving: Llama replicas behind serve deployments.

The build's serving north star (BASELINE.md: "Serve Llama-2-7B JAX
replicas autoscaled on v5e"): a deployment class wrapping a jitted
Llama decode (models/llama.py generate — prefill + while_loop KV-cache
steps), with request batching via the serve batching queue and an
optional device mesh per replica (tensor-parallel serving = a replica
whose mesh has a nontrivial `tensor` axis; cf. serve/_private/replica.py
in the reference for the replica wrapper shape)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


def _family_for(cfg):
    """ONE config-type -> (model class, serving sharding rules) map so
    model construction and mesh sharding can never disagree (a missed
    dispatch site would silently replicate expert weights)."""
    from ray_tpu.models.llama import Llama, llama_sharding_rules
    from ray_tpu.models.mixtral import (Mixtral, MixtralConfig,
                                        mixtral_sharding_rules)
    if isinstance(cfg, MixtralConfig):
        return Mixtral, mixtral_sharding_rules(fsdp=False)
    return Llama, llama_sharding_rules(fsdp=False)


class LlamaDeployment:
    """Deployment-ready Llama wrapper: __init__ builds/loads the model,
    __call__ generates. Wrap with @serve.deployment at use site so
    num_replicas/autoscaling stay caller-controlled."""

    def __init__(self, config=None, params=None, max_new_tokens: int = 64,
                 temperature: float = 0.0, stream_chunk: int = 8,
                 use_engine: bool = True, max_slots: int = 16,
                 page_size: int = 64, n_pages: Optional[int] = None,
                 decode_chunk: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 prefix_cache: bool = False,
                 spec_len: int = 0, spec_ngram: int = 3,
                 deadline_s: Optional[float] = None,
                 max_queued: Optional[int] = None,
                 max_retries: int = 2,
                 retry_backoff_s: float = 0.02,
                 num_engine_replicas: int = 1,
                 pool_auto_restart: bool = True,
                 tensor_parallel: int = 1,
                 expert_parallel: int = 1,
                 autoscale: bool = False,
                 autoscale_max_replicas: Optional[int] = None,
                 autoscale_policy: Optional[Dict[str, Any]] = None,
                 autoscale_interval_s: float = 0.5,
                 autoscale_provider=None,
                 engine_stall_deadline_s: Optional[float] = None,
                 watchdog_interval_s: Optional[float] = None,
                 overlap: Optional[bool] = None,
                 fleet: int = 0,
                 fleet_lease_ttl_s: float = 2.0,
                 kv_dtype: Optional[str] = None,
                 disaggregate: bool = False,
                 prefill_replicas: Optional[int] = None,
                 decode_replicas: Optional[int] = None,
                 kv_pull_deadline_s: Optional[float] = None,
                 kv_pull_backoff_s: Optional[float] = None):
        import jax
        from ray_tpu.models.llama import llama_tiny
        self.cfg = config or llama_tiny()
        # any Llama-shaped family serves through the same decode stack
        model_cls, self._sharding_rules = _family_for(self.cfg)
        self.model = model_cls(self.cfg)
        if params is None:
            import jax.numpy as jnp
            params = self.model.init(
                jax.random.PRNGKey(0),
                jnp.zeros((1, 8), jnp.int32))
        self.params = params
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        # tokens per device round trip when streaming: each chunk pays
        # one host-sync latency, so bigger chunks raise steady-state
        # tok/s at the cost of burstier delivery (TTFT is unaffected)
        self.stream_chunk = stream_chunk
        self.mesh = None
        # Continuous batching (serve/engine.py): requests join/leave
        # the decode batch at token granularity instead of riding
        # whole-call batches (supersedes @serve.batch for LLMs).
        self.use_engine = use_engine
        self._engine = None
        import threading
        self._engine_lock = threading.Lock()
        # Request-lifecycle defaults (serve/engine.py hardening):
        # deadline_s is the deployment-wide per-request deadline
        # (per-call dict payloads can override); max_queued bounds
        # admission so overload sheds fast (EngineOverloaded -> 429
        # at the proxy) instead of silently collapsing TTFT.
        self.deadline_s = deadline_s
        # Data-parallel engine pool (serve/engine_pool.py): N engines
        # behind prefix-affinity routing behave as one logical
        # engine. 1 = plain single engine, no pool in the path.
        if num_engine_replicas < 1:
            raise ValueError("num_engine_replicas must be >= 1")
        self.num_engine_replicas = num_engine_replicas
        self.pool_auto_restart = pool_auto_restart
        # Tensor/expert parallelism WITHIN a replica
        # (serve/sharding.py EngineSharding): each engine shards its
        # weights + head-sharded KV pool over tp*ep devices.
        # Composes orthogonally with num_engine_replicas — 2-D
        # scale-out: shard within a slice x replicate across slices
        # (replica_device_groups hands each pool member its own
        # device group). Validated eagerly so a non-dividing config
        # fails at deployment construction, not first request.
        if tensor_parallel < 1 or expert_parallel < 1:
            raise ValueError("tensor_parallel/expert_parallel must "
                             "be >= 1")
        self.tensor_parallel = int(tensor_parallel)
        self.expert_parallel = int(expert_parallel)
        if self.tensor_parallel > 1 or self.expert_parallel > 1:
            from ray_tpu.serve.sharding import validate_tp
            validate_tp(self.cfg, self.tensor_parallel,
                        self.expert_parallel)
        # SLO-driven pool autoscaling (serve/pool_autoscaler.py):
        # num_engine_replicas becomes the FLOOR, autoscale_max_replicas
        # the ceiling, and a PoolAutoscaler drives the pool between
        # them on queue/shed/TTFT pressure. autoscale_policy overrides
        # individual SLOPolicy fields (e.g. {"ttft_slo_s": 0.2});
        # autoscale_provider supplies the capacity backend (default:
        # ImmediateCapacityProvider — capacity already on the host).
        self.autoscale = autoscale
        self.autoscale_max_replicas = (
            autoscale_max_replicas
            if autoscale_max_replicas is not None
            else max(num_engine_replicas, 4))
        if self.autoscale and \
                self.autoscale_max_replicas < num_engine_replicas:
            raise ValueError("autoscale_max_replicas must be >= "
                             "num_engine_replicas")
        self.autoscale_policy = dict(autoscale_policy or {})
        self.autoscale_interval_s = autoscale_interval_s
        self.autoscale_provider = autoscale_provider
        self._autoscaler = None
        # Pool watchdog (serve/watchdog.py): a replica whose scheduler
        # stops making progress for engine_stall_deadline_s (with work
        # pending) is quarantined (SUSPECT), probed, then force-killed
        # and rebuilt through the pool's death path. None = watchdog
        # off (single-engine deployments have no survivor to resubmit
        # to, so the per-request deadline is the only backstop there).
        if engine_stall_deadline_s is not None \
                and engine_stall_deadline_s <= 0:
            raise ValueError(
                "engine_stall_deadline_s must be > 0 (or None)")
        self.engine_stall_deadline_s = engine_stall_deadline_s
        self.watchdog_interval_s = watchdog_interval_s
        self._watchdog = None
        # Fleet control plane (serve/fleet/): fleet=N swaps the
        # in-process EnginePool for a loopback fleet — a
        # FleetDirectory, N lease-renewing ReplicaAgents (one engine
        # each), and a FleetRouter as the deployment's engine
        # object. Same routing/resubmit core as the pool, but every
        # replica sits behind the transport seam and the
        # lease/fencing state machine, so deployment-level tests
        # exercise exactly the control plane the cross-process
        # harness (tools/chaos_serve.py --fleet) kills for real.
        if fleet < 0:
            raise ValueError("fleet must be >= 0")
        if fleet and num_engine_replicas > 1:
            raise ValueError(
                "fleet= and num_engine_replicas>1 are exclusive — "
                "the fleet IS the replica set")
        if fleet and autoscale:
            # the autoscaler drives the ROUTER here: tickets
            # provision loopback ReplicaAgents (fleet/provider.py),
            # so the provider must be ours — tickets ARE replica ids
            if autoscale_provider is not None:
                raise ValueError(
                    "fleet+autoscale builds its own "
                    "LoopbackAgentProvider (tickets provision fleet "
                    "agents); autoscale_provider is not accepted")
            if self.autoscale_max_replicas < fleet:
                raise ValueError("autoscale_max_replicas must be "
                                 ">= fleet")
        self.fleet = int(fleet)
        self.fleet_lease_ttl_s = float(fleet_lease_ttl_s)
        self._fleet_agents: Dict[str, Any] = {}
        self._fleet_directory = None
        # Prefill/decode disaggregation (serve/engine_pool.py roles):
        # the pool splits into a prefill pool (new requests, TTFT)
        # and a decode pool (streams resumed over the KV-migration
        # handoff) that scale independently. Junk knobs fail HERE,
        # at construction, not on the first pulled page.
        from ray_tpu.serve.kv_migration import validate_pull_knobs
        validate_pull_knobs(kv_pull_deadline_s, kv_pull_backoff_s)
        self.kv_pull_deadline_s = kv_pull_deadline_s
        self.kv_pull_backoff_s = kv_pull_backoff_s
        self.disaggregate = bool(disaggregate)
        if not disaggregate and (prefill_replicas is not None
                                 or decode_replicas is not None):
            raise ValueError(
                "prefill_replicas/decode_replicas require "
                "disaggregate=True")
        if disaggregate:
            if fleet:
                raise ValueError(
                    "disaggregate=True and fleet= are exclusive — "
                    "fleet members carry role metadata but the "
                    "router serves them unified")
            if not prefix_cache:
                raise ValueError(
                    "disaggregate=True requires prefix_cache=True "
                    "(the handoff pulls the prefill replica's "
                    "published pages)")
            p = (int(prefill_replicas)
                 if prefill_replicas is not None else 1)
            d = (int(decode_replicas)
                 if decode_replicas is not None else 1)
            if p < 1 or d < 1:
                raise ValueError("prefill_replicas and "
                                 "decode_replicas must be >= 1")
            if num_engine_replicas not in (1, p + d):
                raise ValueError(
                    f"num_engine_replicas={num_engine_replicas} "
                    f"conflicts with prefill_replicas+decode_"
                    f"replicas={p + d}; omit it (the role split "
                    f"determines pool width)")
            self.num_engine_replicas = p + d
            self.prefill_replicas: Optional[int] = p
            self.decode_replicas: Optional[int] = d
        else:
            self.prefill_replicas = None
            self.decode_replicas = None
        self._engine_opts = dict(
            max_slots=max_slots, page_size=page_size,
            n_pages=n_pages, chunk=decode_chunk or stream_chunk,
            prefill_chunk=prefill_chunk, eos_id=eos_id,
            prefix_cache=prefix_cache,
            spec_len=spec_len, spec_ngram=spec_ngram,
            max_queued=max_queued, max_retries=max_retries,
            retry_backoff_s=retry_backoff_s,
            # with a watchdog guarding the pool, a submit racing a
            # wedged scheduler sheds-and-reroutes instead of parking
            # on the wedged engine's lock
            admit_timeout_s=engine_stall_deadline_s,
            # overlapped hot loop (engine.py): None defers to the
            # engine default (on) and the RAY_TPU_OVERLAP override
            overlap=overlap,
            # KV storage dtype ("fp"/"int8"): int8 halves page bytes
            # at tolerance-gated parity; every replica/fleet engine
            # built from these opts inherits the same pool format
            kv_dtype=kv_dtype)

    def setup_mesh(self, mesh):
        """Called by the serve replica when cfg.mesh is set: shard the
        params over the replica's mesh (tensor-parallel; for Mixtral
        also expert-parallel)."""
        from ray_tpu.mesh.sharding import shard_params
        self.mesh = mesh
        self.params = shard_params(self.params, self._sharding_rules,
                                   mesh)

    def engine(self):
        """The replica's continuous-batching engine (lazy: params may
        be resharded by setup_mesh after __init__). Locked: replicas
        run sync handlers on an executor, so two first requests race
        here — an unlocked check would double-allocate the KV pool."""
        with self._engine_lock:
            if self._engine is None:
                from ray_tpu.serve.engine import LLMEngine
                opts = dict(self._engine_opts)
                # max_slots/n_pages are PER-REPLICA: each pool member
                # is a full engine, so num_engine_replicas=N scales
                # aggregate slots and KV pages N-fold (data-parallel
                # replication adds capacity; it does not reshard one
                # engine's budget).
                if opts["n_pages"] is None:
                    # full residency by default: every slot can reach
                    # prompt+completion without preemption
                    per_seq = -(-self.cfg.max_seq_len
                                // opts["page_size"])
                    opts["n_pages"] = opts["max_slots"] * per_seq + 1
                per = self.tensor_parallel * self.expert_parallel

                def _replica_sharding(idx):
                    # One EngineSharding per replica over its own
                    # device group (2-D scale-out). Recomputed on
                    # restart/scale-up for whatever idx the pool
                    # hands us — the group assignment is pure
                    # arithmetic, so a rebuilt replica idx lands on
                    # the same devices its predecessor used.
                    if per == 1:
                        return None
                    from ray_tpu.serve.sharding import (
                        EngineSharding, replica_device_groups)
                    group = replica_device_groups(idx + 1, per)[idx]
                    return EngineSharding.build(
                        self.cfg, tp=self.tensor_parallel,
                        ep=self.expert_parallel, devices=group)

                if self.fleet:
                    from ray_tpu.serve.fleet.agent import ReplicaAgent
                    from ray_tpu.serve.fleet.directory import (
                        DirectoryClient, FleetDirectory)
                    from ray_tpu.serve.fleet.router import FleetRouter
                    from ray_tpu.serve.fleet.transport import (
                        LoopbackTransport)
                    self._fleet_directory = FleetDirectory(
                        lease_ttl_s=self.fleet_lease_ttl_s)
                    dc = DirectoryClient(LoopbackTransport(
                        self._fleet_directory.handle))
                    agents = self._fleet_agents

                    def tf(addr):
                        # loopback addr = ["loopback", replica_id]
                        return LoopbackTransport(agents[addr[1]].handle)

                    for i in range(self.fleet):
                        rid = f"r{i}"

                        def factory(gen, _i=i, _opts=opts):
                            return LLMEngine(
                                self.model, self.params,
                                temperature=self.temperature,
                                seed=_i,
                                sharding=_replica_sharding(_i),
                                **_opts)

                        agents[rid] = ReplicaAgent(
                            rid, factory, dc,
                            stall_deadline_s=(
                                self.engine_stall_deadline_s)).start()
                    self._engine = FleetRouter(dc, tf)
                    if self.autoscale:
                        import itertools

                        from ray_tpu.serve.fleet.provider import (
                            LoopbackAgentProvider)
                        from ray_tpu.serve.pool_autoscaler import (
                            PoolAutoscaler, SLOPolicy)
                        seq = itertools.count(self.fleet)

                        def spawn_agent(rid, _opts=opts):
                            # provisioning == building + starting a
                            # loopback agent; inserted in the
                            # transport map BEFORE start() so the
                            # router can route the moment the
                            # directory advertises it
                            n = next(seq)

                            def f(gen, _n=n):
                                return LLMEngine(
                                    self.model, self.params,
                                    temperature=self.temperature,
                                    seed=_n,
                                    sharding=_replica_sharding(_n),
                                    **_opts)

                            a = ReplicaAgent(
                                rid, f, dc,
                                stall_deadline_s=(
                                    self.engine_stall_deadline_s))
                            agents[rid] = a
                            return a.start()

                        policy = SLOPolicy(
                            min_replicas=self.fleet,
                            max_replicas=self.autoscale_max_replicas,
                            **self.autoscale_policy)
                        self._autoscaler = PoolAutoscaler(
                            self._engine, policy,
                            LoopbackAgentProvider(spawn_agent)).run(
                                self.autoscale_interval_s)
                elif (self.num_engine_replicas > 1 or self.autoscale
                      or self.disaggregate):
                    from ray_tpu.serve.engine_pool import EnginePool

                    def factory(idx, _opts=opts):
                        return LLMEngine(
                            self.model, self.params,
                            temperature=self.temperature,
                            seed=idx,
                            sharding=_replica_sharding(idx),
                            **_opts)

                    pool_kw: Dict[str, Any] = dict(
                        auto_restart=self.pool_auto_restart,
                        kv_pull_deadline_s=self.kv_pull_deadline_s,
                        kv_pull_backoff_s=self.kv_pull_backoff_s)
                    if self.disaggregate:
                        from ray_tpu.serve.scheduler import (
                            ROLE_DECODE, ROLE_PREFILL)
                        pool_kw.update(
                            share_prefixes=True,
                            roles=([ROLE_PREFILL]
                                   * self.prefill_replicas
                                   + [ROLE_DECODE]
                                   * self.decode_replicas))
                    self._engine = EnginePool(
                        factory, self.num_engine_replicas,
                        **pool_kw)
                    if self.autoscale and self.disaggregate:
                        # one scaler per role over role-filtered pool
                        # views, one shared capacity provider: the
                        # prefill pool chases TTFT/queue, the decode
                        # pool chases ITL/free slots, and they reach
                        # DIFFERENT sizes on the same trace
                        from ray_tpu.serve.engine_pool import (
                            RolePoolView)
                        from ray_tpu.serve.pool_autoscaler import (
                            ImmediateCapacityProvider,
                            PoolAutoscaler, SLOPolicy)
                        ap = dict(self.autoscale_policy)
                        pre_over = dict(ap.pop("prefill", {}))
                        dec_over = dict(ap.pop("decode", {}))
                        provider = (self.autoscale_provider
                                    or ImmediateCapacityProvider())
                        self._autoscaler = {}
                        for role, floor, over in (
                                (ROLE_PREFILL, self.prefill_replicas,
                                 pre_over),
                                (ROLE_DECODE, self.decode_replicas,
                                 dec_over)):
                            policy = SLOPolicy(
                                min_replicas=floor,
                                max_replicas=(
                                    self.autoscale_max_replicas),
                                **{**ap, **over})
                            self._autoscaler[role] = PoolAutoscaler(
                                RolePoolView(self._engine, role),
                                policy, provider).run(
                                    self.autoscale_interval_s)
                    elif self.autoscale:
                        from ray_tpu.serve.pool_autoscaler import (
                            PoolAutoscaler, SLOPolicy)
                        policy = SLOPolicy(
                            min_replicas=self.num_engine_replicas,
                            max_replicas=self.autoscale_max_replicas,
                            **self.autoscale_policy)
                        self._autoscaler = PoolAutoscaler(
                            self._engine, policy,
                            self.autoscale_provider).run(
                                self.autoscale_interval_s)
                    if self.engine_stall_deadline_s is not None:
                        from ray_tpu.serve.watchdog import PoolWatchdog
                        self._watchdog = PoolWatchdog(
                            self._engine,
                            stall_deadline_s=(
                                self.engine_stall_deadline_s),
                            poll_interval_s=(
                                self.watchdog_interval_s)).run()
                else:
                    self._engine = LLMEngine(
                        self.model, self.params,
                        temperature=self.temperature,
                        sharding=_replica_sharding(0),
                        **opts).start()
            return self._engine

    def autoscaler(self):
        """The attached PoolAutoscaler (None until the lazy engine is
        built or when autoscale=False). Disaggregated deployments
        return a ``{"prefill": ..., "decode": ...}`` dict — one
        scaler per role."""
        return self._autoscaler

    def watchdog(self):
        """The attached PoolWatchdog (None until the lazy engine is
        built or when engine_stall_deadline_s is None)."""
        return self._watchdog

    def serve_stats(self) -> dict:
        """Replica metrics hook (merged into Replica.stats() under
        "user"): engine counters plus live slot occupancy, without
        forcing a lazy engine into existence."""
        if not self.use_engine or self._engine is None:
            return {"engine": None}
        eng = self._engine
        if self.fleet:
            # FleetRouter: members are behind the transport seam, so
            # the aggregate comes from their ADVERTISED reports (the
            # directory snapshot), not from reaching into engine
            # locks — the same information a remote router would
            # have.
            out = dict(eng.load_report())
            out.update(consistent=False,
                       max_queued=self._engine_opts["max_queued"],
                       fleet=eng.pool_stats())
            return {"engine": out}
        from ray_tpu.serve.engine_pool import EnginePool
        if isinstance(eng, EnginePool):
            out: dict = dict(eng.stats)
            slots_live = slots_total = 0
            pages_free = pages_total = 0
            for rep_eng in eng.engines():
                locked = rep_eng._lock.acquire(timeout=0.05)
                try:
                    slots_live += sum(1 for s in rep_eng.slots
                                      if s is not None)
                    slots_total += rep_eng.S
                    pages_free += rep_eng.alloc.n_free
                    pages_total += rep_eng.alloc.n_pages - 1
                finally:
                    if locked:
                        rep_eng._lock.release()
            out.update(slots_live=slots_live,
                       slots_total=slots_total,
                       pages_free=pages_free,
                       pages_total=pages_total,
                       consistent=False,
                       max_queued=self._engine_opts["max_queued"],
                       max_retries=self._engine_opts["max_retries"],
                       retry_backoff_s=self._engine_opts[
                           "retry_backoff_s"],
                       pool=eng.pool_stats())
            ps = eng.prefix_stats()
            if ps:
                out["prefix_cache"] = ps
            return {"engine": out}
        # Best-effort lock: the scheduler holds eng._lock across
        # dispatch AND blocking readbacks (seconds under load), and
        # this runs as a sync method ON the replica event loop —
        # waiting here would stall request handling and make the
        # controller's 2s-timeout stats polls misread a busy replica
        # as idle. Lock-free reads of these ints/lists are safe
        # (GIL), just possibly torn across fields.
        locked = eng._lock.acquire(timeout=0.05)
        try:
            live = sum(1 for s in eng.slots if s is not None)
            out = dict(eng.stats)
            free, total = eng.alloc.n_free, eng.alloc.n_pages - 1
        finally:
            if locked:
                eng._lock.release()
        out.update(slots_live=live, slots_total=eng.S,
                   pages_free=free, pages_total=total,
                   consistent=locked,
                   max_queued=eng.max_queued,
                   max_retries=eng.max_retries,
                   retry_backoff_s=eng.retry_backoff_s)
        if eng.prefix_cache is not None:
            out["prefix_cache"] = eng.prefix_cache.stats()
        return {"engine": out}

    def load_report(self) -> Optional[dict]:
        """Compact load snapshot for the controller's replica table
        (engine or pool-aggregate; None before the lazy engine
        exists — an idle replica carries no load)."""
        if not self.use_engine or self._engine is None:
            return None
        rpt = dict(self._engine.load_report())
        # the digest is an intra-pool affinity signal, not something
        # the deployment-level replica table needs to carry around
        rpt.pop("prefix_digest", None)
        return rpt

    def _request_args(self, payload):
        """(prompt_ids, max_new_tokens, deadline_s, session_id,
        trace_id): a request is a plain token-id list, or a dict
        carrying per-request lifecycle/routing overrides
        ({"prompt_ids": [...], "max_new_tokens": n, "deadline_s": s,
        "session_id": "u123", "trace_id": "ab12..."}) — what the
        HTTP proxy posts through. session_id drives engine-pool
        stickiness and is ignored by a single engine; trace_id is
        the proxy-minted request-scope id stamped into the engine
        event log (serve/obs.py)."""
        if isinstance(payload, dict):
            prompt_ids = payload.get("prompt_ids",
                                     payload.get("prompt"))
            if prompt_ids is None:
                raise ValueError(
                    "request dict needs a 'prompt_ids' key")
            mnt = int(payload.get("max_new_tokens",
                                  self.max_new_tokens))
            dl = payload.get("deadline_s", self.deadline_s)
            sid = payload.get("session_id")
            tid = payload.get("trace_id")
            return list(prompt_ids), mnt, (
                float(dl) if dl is not None else None), (
                str(sid) if sid is not None else None), (
                str(tid) if tid is not None else None)
        return (list(payload), self.max_new_tokens, self.deadline_s,
                None, None)

    def _submit(self, ids, mnt, dl, sid=None, tid=None):
        kw: Dict[str, Any] = dict(max_new_tokens=mnt, deadline_s=dl)
        if sid is not None and (self.num_engine_replicas > 1
                                or self.fleet):
            kw["session_id"] = sid
        if tid is not None:
            kw["trace_id"] = tid
        return self.engine().submit(ids, **kw)

    def _weights_tag(self, h) -> str:
        """``generation:weights_id`` of whatever served ``h`` (the
        X-Model-Generation header value). Handle-first: the pool/
        engine handles know their serving replica; fall back to the
        deployment's own engine surface (single engine), then to the
        never-swapped default."""
        tag = getattr(h, "weights_tag", None)
        if tag:
            return tag
        eng = self.engine()
        gen = getattr(eng, "weight_generation", None)
        if gen is not None:
            return f"{gen}:{getattr(eng, 'weights_id', None)}"
        return "0:g0"

    def __call__(self, prompt_ids: List[int]) -> List[int]:
        """One request: token ids in, prompt+generated ids out.

        A dict payload with ``"echo_replica": true`` (injected by the
        HTTP proxy when the client sends an ``X-Replica`` request
        header) gets ``{"ids": [...], "replica": "<id>:<gen>"}``
        back instead of the bare list — the tag names which replica
        incarnation actually served the request (pool ``idx:gen``,
        fleet ``replica_id:generation``, single engine ``0:0``), so
        a client can see a failover land on a different
        incarnation."""
        if self.use_engine:
            ids, mnt, dl, sid, tid = self._request_args(prompt_ids)
            h = self._submit(ids, mnt, dl, sid, tid)
            gen = h.result()
            out = list(ids) + gen
            echo_rep = isinstance(prompt_ids, dict) \
                and prompt_ids.get("echo_replica")
            echo_gen = isinstance(prompt_ids, dict) \
                and prompt_ids.get("echo_generation")
            if echo_rep or echo_gen:
                resp: Dict[str, Any] = {"ids": out}
                if echo_rep:
                    resp["replica"] = getattr(
                        h, "replica_tag", None) or "0:0"
                if echo_gen:
                    resp["generation"] = self._weights_tag(h)
                return resp
            return out
        import jax.numpy as jnp
        from ray_tpu.models.llama import generate
        prompt = jnp.asarray([prompt_ids], jnp.int32)
        out = generate(self.model, self.params, prompt,
                       max_new_tokens=self.max_new_tokens,
                       temperature=self.temperature)
        return np.asarray(out[0]).tolist()

    def stream(self, prompt_ids: List[int]):
        """Streaming request: yields each generated token id as soon
        as it is sampled (token-at-a-time decode; serve wraps this
        generator in a StreamingResponse and the HTTP proxy in a
        chunked ndjson response).

        ``"echo_replica": true`` in a dict payload makes the FIRST
        yield ``{"replica": "<id>:<gen>"}`` instead of a token — the
        proxy pops it into the ``X-Replica`` response header before
        committing the chunked response, so streaming clients get
        the same which-incarnation-served-me signal unary clients
        do."""
        if self.use_engine:
            ids, mnt, dl, sid, tid = self._request_args(prompt_ids)
            h = self._submit(ids, mnt, dl, sid, tid)
            echo_rep = isinstance(prompt_ids, dict) \
                and prompt_ids.get("echo_replica")
            echo_gen = isinstance(prompt_ids, dict) \
                and prompt_ids.get("echo_generation")
            if echo_rep or echo_gen:
                marker: Dict[str, Any] = {}
                if echo_rep:
                    marker["replica"] = getattr(
                        h, "replica_tag", None) or "0:0"
                if echo_gen:
                    marker["generation"] = self._weights_tag(h)
                yield marker
            try:
                yield from h.stream()
            except GeneratorExit:
                # The client disconnected: the replica abandons the
                # stream and garbage-collects this generator
                # (controller.py _drain_sync), which closes it here.
                # Cancel so the slot and its KV pages free NOW — an
                # abandoned stream must not decode to completion.
                h.cancel()
                raise
            return
        import jax.numpy as jnp
        from ray_tpu.models.llama import generate_stream
        prompt = jnp.asarray([prompt_ids], jnp.int32)
        for tok in generate_stream(self.model, self.params, prompt,
                                   max_new_tokens=self.max_new_tokens,
                                   temperature=self.temperature,
                                   chunk_size=self.stream_chunk):
            yield int(tok[0])

    def generate_batch(self, prompts: List[List[int]]) -> List[List[int]]:
        """Batched generation for throughput serving: prompts are
        bucketed by length and each bucket decodes as one batch on the
        chip (one MXU-efficient kernel instead of B tiny ones).

        Bucketing instead of padding: the model applies only a causal
        mask, so padding a shorter prompt would let it attend to the
        pad tokens and change its completion versus an unbatched
        call — same-length batching is the correctness-preserving way
        to batch (serving clients typically use fixed prompt shapes,
        giving one bucket)."""
        if self.use_engine:
            eng = self.engine()
            hs = [eng.submit(p, max_new_tokens=self.max_new_tokens)
                  for p in prompts]
            return [h.result() for h in hs]
        import jax.numpy as jnp
        from ray_tpu.models.llama import generate
        buckets: Dict[int, List[int]] = {}
        for i, p in enumerate(prompts):
            buckets.setdefault(len(p), []).append(i)
        results: List[Optional[List[int]]] = [None] * len(prompts)
        for plen, idxs in buckets.items():
            batch = np.asarray([prompts[i] for i in idxs], np.int32)
            out = generate(self.model, self.params,
                           jnp.asarray(batch),
                           max_new_tokens=self.max_new_tokens,
                           temperature=self.temperature)
            gen = np.asarray(out)[:, plen:]
            for row, i in zip(gen, idxs):
                results[i] = row.tolist()
        return results
