"""LLM serving: Llama replicas behind serve deployments.

The build's serving north star (BASELINE.md: "Serve Llama-2-7B JAX
replicas autoscaled on v5e"): a deployment class wrapping a jitted
Llama decode (models/llama.py generate — prefill + while_loop KV-cache
steps), with request batching via the serve batching queue and an
optional device mesh per replica (tensor-parallel serving = a replica
whose mesh has a nontrivial `tensor` axis; cf. serve/_private/replica.py
in the reference for the replica wrapper shape)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class LlamaDeployment:
    """Deployment-ready Llama wrapper: __init__ builds/loads the model,
    __call__ generates. Wrap with @serve.deployment at use site so
    num_replicas/autoscaling stay caller-controlled."""

    def __init__(self, config=None, params=None, max_new_tokens: int = 64,
                 temperature: float = 0.0):
        import jax
        from ray_tpu.models.llama import Llama, llama_tiny
        self.cfg = config or llama_tiny()
        self.model = Llama(self.cfg)
        if params is None:
            import jax.numpy as jnp
            params = self.model.init(
                jax.random.PRNGKey(0),
                jnp.zeros((1, 8), jnp.int32))
        self.params = params
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.mesh = None

    def setup_mesh(self, mesh):
        """Called by the serve replica when cfg.mesh is set: shard the
        params tensor-parallel over the replica's mesh."""
        from ray_tpu.mesh.sharding import shard_params
        from ray_tpu.models.llama import llama_sharding_rules
        self.mesh = mesh
        self.params = shard_params(self.params,
                                   llama_sharding_rules(fsdp=False),
                                   mesh)

    def __call__(self, prompt_ids: List[int]) -> List[int]:
        """One request: token ids in, generated ids out."""
        import jax.numpy as jnp
        from ray_tpu.models.llama import generate
        prompt = jnp.asarray([prompt_ids], jnp.int32)
        out = generate(self.model, self.params, prompt,
                       max_new_tokens=self.max_new_tokens,
                       temperature=self.temperature)
        return np.asarray(out[0]).tolist()
