"""EnginePool: N LLMEngine replicas behaving as ONE logical engine.

After chunked prefill, the radix prefix cache, spec decode, and
lifecycle hardening, the serving stack still terminated in a single
``LLMEngine`` — one replica was both the throughput ceiling and the
blast radius. This module is the data-parallel control plane that
removes that ceiling the way the reference runtime scales serving:
many identical accelerator-bound workers behind a thin, load-aware
router (Ray's replica sets + power-of-two-choices; Podracer-style
TPU fleets).

Routing policy, in precedence order (``_route``):

1. **Session stickiness** — a ``session_id`` keeps hitting the
   replica that served it last (its KV prefix lives there), unless
   that replica is gone or saturated.
2. **Longest-prefix affinity** — each replica's ``load_report()``
   carries a digest of its radix prefix cache (rolling path hashes,
   ``prefix_cache.path_hashes``). The prompt is hashed once and the
   replica holding its longest cached prefix wins, so the PR-2 radix
   cache COMPOUNDS across the fleet instead of fragmenting: without
   affinity, a shared system prompt gets re-prefilled on every
   replica it happens to land on.
3. **Spill** — when the affinity target is saturated (bounded queue
   full), the request spills to the least-loaded healthy replica
   instead of queueing behind its hot spot. The spill target then
   caches the prefix too, so sustained hot prefixes replicate
   themselves exactly as wide as their load requires.
4. **Power-of-two-choices** on least outstanding tokens — the
   classic load-balancing result: sampling two replicas and taking
   the lighter one gets within a constant of optimal at O(1) cost.

Replica lifecycle, owned by the pool:

- **Draining** (``drain(idx)``): the replica admits nothing new
  (direct submits fail typed ``EngineDraining``), finishes in-flight
  work, shuts down, and is rebuilt from the factory — a rolling
  config update with zero failed requests when work fits the drain
  budget.
- **Failure recovery**: when a replica dies (device loss, injected
  ``ReplicaKilled``, any global ``_fail_all``), requests that have
  not streamed a single token resubmit transparently to a healthy
  replica (at-most-once delivery holds: nothing was observed, so
  the retry cannot duplicate). Requests that already streamed fail
  TYPED with ``EngineShutdown`` — replaying a partial greedy stream
  exactly-once cannot be guaranteed, so the pool refuses to guess.
- **Aggregate shed**: when every healthy replica sheds, the pool
  raises one ``EngineOverloaded`` whose ``retry_after_s`` is the MAX
  over replicas — an honest Retry-After even when only the slowest
  replica is the bottleneck (the proxy maps it to 429).

The pool mirrors the single-engine surface the deployment layer uses
(``submit``/``stats``/``ttfts_s``/``prefix_stats``/``spec_stats``/
``lifecycle_stats``/``shutdown``), so ``num_engine_replicas=N`` is a
one-knob change in ``serve/llm.py``.
"""
from __future__ import annotations

import collections
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ray_tpu.serve import kv_migration, obs
from ray_tpu.serve.errors import (DeadlineExceeded, EngineDraining,
                                  EngineOverloaded, EngineShutdown,
                                  PoolDegraded, RequestCancelled,
                                  RequestError)
from ray_tpu.serve.fleet.routing import (Candidate, ResubmitPolicy,
                                         select_candidate)
from ray_tpu.serve.prefix_cache import path_hashes
from ray_tpu.serve.scheduler import (LANE_BATCH, LANE_ONLINE,
                                     REPLICA_ROLES, ROLE_DECODE,
                                     ROLE_PREFILL, ROLE_UNIFIED)

ROUTED = "serve_pool_routed_total"
AFFINITY_HITS = "serve_pool_affinity_hits_total"
STICKY_HITS = "serve_pool_sticky_hits_total"
SPILLS = "serve_pool_spills_total"
REQUEUES = "serve_pool_requeues_total"
REPLICA_DEATHS = "serve_pool_replica_deaths_total"
DRAINS = "serve_pool_drains_total"
RESTARTS = "serve_pool_restarts_total"
ALL_SHED = "serve_pool_all_shed_total"
FREE_SLOTS = "serve_pool_replica_free_slots"
QUEUE_DEPTH = "serve_pool_replica_queue_depth"
BATCH_QUEUE_DEPTH = "serve_pool_replica_batch_queue_depth"
CAPACITY_HINT_ERRORS = "serve_pool_capacity_hint_errors_total"
SUSPECTS = "serve_pool_suspect_total"
WEDGED = "serve_pool_wedged_total"
WEDGE_LATENCY = "serve_pool_wedge_detect_latency_s"
DISAGG_HANDOFFS = "serve_disagg_handoffs_total"
DISAGG_FALLBACKS = "serve_disagg_handoff_fallbacks_total"

# Role sets the disaggregated router selects over: new prompts land
# on the prefill side, handed-off streams on the decode side. UNIFIED
# replicas serve both — they are the bridge that keeps a half-rolled
# (or degraded) disaggregated pool available.
_PREFILL_SIDE = (ROLE_PREFILL, ROLE_UNIFIED)
_DECODE_SIDE = (ROLE_DECODE, ROLE_UNIFIED)

_METRICS: Optional[dict] = None


def _metrics() -> dict:
    """Lazy module-level metric singletons, re-created if a test's
    ``clear_registry()`` dropped them (same pattern as the engine and
    prefix-cache modules)."""
    global _METRICS
    from ray_tpu.util import metrics
    if (_METRICS is None
            or metrics.registry().get(ROUTED)
            is not _METRICS["routed"]):
        _METRICS = {
            "routed": metrics.Counter(
                ROUTED, "Requests routed by the engine pool"),
            "affinity_hits": metrics.Counter(
                AFFINITY_HITS, "Routes landing on a replica already "
                "holding a prefix of the prompt"),
            "sticky_hits": metrics.Counter(
                STICKY_HITS, "Routes resolved by session stickiness"),
            "spills": metrics.Counter(
                SPILLS, "Affinity targets saturated; request spilled "
                "to another replica"),
            "requeues": metrics.Counter(
                REQUEUES, "Unstreamed requests resubmitted after a "
                "replica death"),
            "replica_deaths": metrics.Counter(
                REPLICA_DEATHS, "Replica engines observed dead"),
            "drains": metrics.Counter(
                DRAINS, "Replica drains started"),
            "restarts": metrics.Counter(
                RESTARTS, "Replica engines rebuilt from the factory"),
            "all_shed": metrics.Counter(
                ALL_SHED, "Pool-aggregate sheds (every healthy "
                "replica refused admission)"),
            "free_slots": metrics.Gauge(
                FREE_SLOTS, "Free decode slots per replica",
                tag_keys=("replica",)),
            "queue_depth": metrics.Gauge(
                QUEUE_DEPTH, "Admission queue depth per replica "
                "(ONLINE lane — the saturation/autoscaling signal)",
                tag_keys=("replica",)),
            "batch_queue_depth": metrics.Gauge(
                BATCH_QUEUE_DEPTH, "BATCH-lane queue depth per "
                "replica (preemptible backlog; excluded from "
                "saturation and autoscaling signals)",
                tag_keys=("replica",)),
            "capacity_hint_errors": metrics.Counter(
                CAPACITY_HINT_ERRORS, "capacity_hint_fn raised; the "
                "pool fell back to the pending-backoff ETA"),
            "suspects": metrics.Counter(
                SUSPECTS, "Replicas quarantined SUSPECT by the "
                "watchdog (stale heartbeat with work pending)"),
            "wedged": metrics.Counter(
                WEDGED, "Replicas declared WEDGED and force-killed "
                "by the watchdog"),
            "wedge_latency": metrics.Histogram(
                WEDGE_LATENCY, "Seconds from last observed progress "
                "to the WEDGED declaration",
                boundaries=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                            10.0, 30.0)),
            "disagg_handoffs": metrics.Counter(
                DISAGG_HANDOFFS, "Prefill->decode stream handoffs "
                "submitted over the KV-migration path"),
            "disagg_fallbacks": metrics.Counter(
                DISAGG_FALLBACKS, "Handoffs aborted typed and fallen "
                "back to decoding in place"),
        }
    return _METRICS


HEALTHY = "healthy"
DRAINING = "draining"
DEAD = "dead"
# Watchdog quarantine (serve/watchdog.py): the replica's progress
# heartbeat went stale WITH work pending. Routing, capacity counts,
# and the autoscaler's healthy_replicas signal all skip it for free
# (everything filters on HEALTHY); the watchdog either clears it back
# to HEALTHY on probed progress or escalates to the death path.
SUSPECT = "suspect"
# Scale-down tombstone: the replica was drained and shut down ON
# PURPOSE and will not be rebuilt; its slot index may be reused by a
# later scale-up. Kept in the table so pool-wide quiescence checks
# still cover its engine.
RETIRED = "retired"
# Crash-loop terminal state: the replica died ``max_restarts`` times
# and the pool stopped rebuilding it. Routing skips it; a human (or
# ``restart_dead()``) has to intervene.
DEGRADED = "degraded"


class _Replica:
    """One pool slot: a live engine plus its lifecycle state.
    ``generation`` counts factory rebuilds (drain restarts + failure
    restarts) so tests can assert a replica was actually replaced."""

    __slots__ = ("idx", "engine", "state", "deaths", "generation",
                 "role")

    def __init__(self, idx: int, engine, state: str = HEALTHY,
                 deaths: int = 0, generation: int = 0,
                 role: str = ROLE_UNIFIED):
        self.idx = idx
        self.engine = engine
        self.state = state
        self.deaths = deaths
        self.generation = generation
        self.role = role


class PoolRequestHandle(ResubmitPolicy):
    """Client-side view of a pooled request. Mirrors the engine's
    ``RequestHandle`` surface (stream/result/cancel/done/error/
    ttft_s) and adds the recovery loop: iterating ``stream()`` (or
    ``result()``) transparently resubmits the request to a healthy
    replica when its replica dies BEFORE any token was delivered;
    after first delivery a replica death fails typed
    ``EngineShutdown`` — never a silent hang, never a duplicated
    token. The at-most-once guard itself (budget, deadline carry,
    partial-stream refusal) is ``fleet.routing.ResubmitPolicy``,
    shared with the process-separated ``FleetRouter``."""

    def __init__(self, pool: "EnginePool", prompt: List[int],
                 max_new_tokens: int, deadline_s: Optional[float],
                 session_id: Optional[str],
                 trace_id: Optional[str] = None,
                 priority: str = LANE_ONLINE):
        super().__init__(prompt, max_new_tokens, deadline_s,
                         session_id, trace_id,
                         max_resubmits=pool.max_resubmits)
        self._pool = pool
        self._priority = priority
        self._rep: Optional[_Replica] = None
        self._inner = None
        # Disaggregated two-leg service (set by the pool at submit
        # when the request was split): leg 1 streams ONE bridging
        # token from the prefill pool, leg 2 resumes the stream on a
        # decode replica over the KV-migration handoff path.
        self._disagg = False

    # ------------------------------------------------------- consuming

    def stream(self):
        """Yield generated token ids; recover across replica deaths
        while the at-most-once guard allows (zero tokens delivered)."""
        if self._disagg:
            yield from self._stream_disagg()
            return
        while True:
            rep, inner = self._rep, self._inner
            try:
                for tok in inner.stream():
                    self._note_token(tok)
                    yield tok
                self._finished = True
                return
            except GeneratorExit:
                # consumer closed the stream (disconnect): not a
                # failure, and certainly not a resubmission trigger
                raise
            except (RequestCancelled, DeadlineExceeded,
                    EngineOverloaded, EngineDraining) as e:
                # request-level outcomes: the pool never second-
                # guesses an explicit cancel/deadline/shed
                self._fail(e)
                raise
            except BaseException as e:
                # EngineShutdown, a contained-fault wrapper, or the
                # RAW global error a _fail_all delivered (e.g.
                # ReplicaKilled). Replica death is judged by the
                # engine, not the exception type.
                if not self._pool._note_replica_death(rep):
                    self._fail(e)
                    raise
                if self._generated or self._cancelled:
                    raise self._partial_stream_error(
                        str(rep.idx), e) from e
                self._resubmit(e)      # raises typed when impossible

    def _stream_disagg(self):
        """Two-leg disaggregated stream. Leg 1 (already submitted by
        the pool): one bridging token on the prefill side — the
        engine retires the slot after it, publishing the prompt's
        full KV pages into the donor's prefix cache. Leg 2: the rest
        of the stream on the decode side, admitted with a
        finished-prefill push hint so its KV lands over
        ``kv_migration.pull_prefix`` (mid-offset resume at full
        prompt length) instead of recomputing. Greedy fp32 decoding
        is deterministic, so the stitched stream is token-identical
        to single-replica service.

        Failure contract (the tentpole's "cost time, never
        correctness"): every way leg 2 can fail BEFORE its first
        token is one typed abort that falls back to decoding in
        place on the prefill replica (then, if the donor itself is
        gone, to any healthy replica via plain prefill). After leg 2
        streams, a death fails typed exactly like the base loop —
        per-leg at-most-once."""
        pool = self._pool
        first: Optional[int] = None
        # ---- leg 1: bridging token from the prefill pool
        while True:
            rep, inner = self._rep, self._inner
            try:
                for tok in inner.stream():
                    self._note_token(tok)
                    first = tok
                break
            except GeneratorExit:
                raise
            except (RequestCancelled, DeadlineExceeded,
                    EngineOverloaded, EngineDraining) as e:
                self._fail(e)
                raise
            except BaseException as e:
                if not pool._note_replica_death(rep):
                    self._fail(e)
                    raise
                if first is not None:
                    break     # token landed; only the donor is gone
                if self._cancelled:
                    raise self._partial_stream_error(
                        str(rep.idx), e) from e
                deadline = self._check_resubmit(e)
                pool._count_requeue(trace_id=self._trace_id)
                try:
                    self._rep, self._inner = pool._submit_leg(
                        self._prompt, 1, deadline, None,
                        trace_id=self._trace_id, roles=_PREFILL_SIDE,
                        fallback_any=True)
                except BaseException as e2:
                    self._fail(e2)
                    raise
        if first is None:
            # engine contract: a non-failing stream emits >= 1 token
            err = EngineShutdown(
                "prefill leg closed without a token")
            self._fail(err)
            raise err
        yield first
        if self._mnt <= 1 or self._cancelled:
            self._finished = True
            return
        # ---- handoff: decode leg resumes at full prompt length
        donor = self._rep
        prompt2 = self._prompt + [first]
        mnt2 = self._mnt - 1
        self._rep = self._inner = None
        self._hand_off(donor, prompt2, mnt2)
        # ---- leg 2: stream on the decode side
        leg2_tokens = 0
        while True:
            rep, inner = self._rep, self._inner
            try:
                for tok in inner.stream():
                    if leg2_tokens == 0:
                        pool._note_handoff_first_token(
                            rep, trace_id=self._trace_id)
                    leg2_tokens += 1
                    self._note_token(tok)
                    yield tok
                self._finished = True
                return
            except GeneratorExit:
                raise
            except (RequestCancelled, DeadlineExceeded,
                    EngineOverloaded, EngineDraining) as e:
                self._fail(e)
                raise
            except BaseException as e:
                if not pool._note_replica_death(rep):
                    self._fail(e)
                    raise
                if leg2_tokens or self._cancelled:
                    raise self._partial_stream_error(
                        str(rep.idx), e) from e
                self._check_resubmit(e)
                pool._count_requeue(trace_id=self._trace_id)
                self._hand_off(donor, prompt2, mnt2)

    def _hand_off(self, donor: Optional[_Replica],
                  prompt2: List[int], mnt2: int) -> None:
        """Submit the decode leg: decode-side route with the
        finished-prefill push hint, then the typed-abort fallback
        ladder — decode in place on the donor, then any healthy
        replica (plain prefill). Raises typed only when no replica
        at all can take the stream."""
        pool = self._pool
        deadline = self._remaining_deadline(None) \
            if self._deadline_s is not None else None
        donor_live = (donor is not None
                      and not getattr(donor.engine, "_stopped", True))
        hint = None
        if donor_live:
            hint = kv_migration.prefill_push_hint(
                self._prompt, getattr(donor.engine, "Pg", 0),
                replica_idx=donor.idx)
        try:
            self._rep, self._inner = pool._submit_leg(
                prompt2, mnt2, deadline, self._session_id,
                trace_id=self._trace_id, roles=_DECODE_SIDE,
                pull=hint,
                exclude={donor.idx} if donor_live else None)
            pool._note_handoff(donor, self._rep,
                               trace_id=self._trace_id)
            return
        except (RequestCancelled, DeadlineExceeded) as e:
            self._fail(e)
            raise
        except BaseException as e:
            cause = e
        # Typed abort -> decode in place on the prefill replica: its
        # prefix cache already holds the prompt's pages, so this is a
        # local-hit residual prefill, not a recompute.
        pool._note_handoff_fallback(donor, cause,
                                    trace_id=self._trace_id)
        if donor_live:
            try:
                self._rep, self._inner = pool._submit_once(
                    prompt2, mnt2, deadline, None,
                    trace_id=self._trace_id, target_idx=donor.idx,
                    record_sticky=False)
                return
            except (RequestCancelled, DeadlineExceeded) as e:
                self._fail(e)
                raise
            except BaseException:
                pass          # donor died under us: last rung below
        # Donor gone too: any healthy replica, plain prefill.
        try:
            self._rep, self._inner = pool._submit_once(
                prompt2, mnt2, deadline, self._session_id,
                trace_id=self._trace_id)
        except BaseException as e:
            self._fail(e)
            raise

    # ------------------------------------------------------- lifecycle

    def cancel(self) -> bool:
        self._cancelled = True
        inner = self._inner
        return inner.cancel() if inner is not None else False

    @property
    def replica_idx(self) -> Optional[int]:
        return self._rep.idx if self._rep is not None else None

    @property
    def replica_tag(self) -> Optional[str]:
        """``idx:generation`` of the serving replica incarnation —
        a resubmit that lands on a rebuilt replica of the SAME idx
        still shows a different tag (the X-Replica header value)."""
        rep = self._rep
        return (f"{rep.idx}:{rep.generation}"
                if rep is not None else None)

    @property
    def weights_tag(self) -> Optional[str]:
        """``generation:weights_id`` of the serving replica's engine
        (the X-Model-Generation header value). A resubmit that lands
        mid-rollout on a replica serving a different payload shows a
        different tag."""
        rep = self._rep
        eng = getattr(rep, "engine", None) if rep is not None else None
        gen = getattr(eng, "weight_generation", None)
        if gen is None:
            return None
        return f"{gen}:{getattr(eng, 'weights_id', None)}"

    @property
    def logprobs(self) -> Optional[List[float]]:
        """Per-token sampling logprobs from the serving replica's
        handle (engines built with ``capture_logprobs=True``; None
        otherwise). A death-triggered resubmit regenerates from
        scratch on the new replica, so the list always reflects one
        engine's aligned token stream — never a stitched mix."""
        inner = self._inner
        if inner is None:
            return None
        return getattr(inner, "logprobs", None)

    # -------------------------------------------------------- internal

    def _resubmit(self, cause: BaseException) -> None:
        deadline = self._check_resubmit(cause)
        self._pool._count_requeue(trace_id=self._trace_id)
        try:
            self._rep, self._inner = self._pool._submit_once(
                self._prompt, self._mnt, deadline, self._session_id,
                trace_id=self._trace_id, priority=self._priority)
        except BaseException as e:
            self._fail(e)
            raise

    def _attach(self, rep: _Replica, inner) -> None:
        self._rep, self._inner = rep, inner


class EnginePool:
    """N ``LLMEngine`` replicas as one logical engine (module
    docstring has the full routing + lifecycle contract).

    Parameters
    ----------
    engine_factory: ``f(replica_idx) -> LLMEngine`` building ONE
        replica (not started; the pool starts it). Called again on
        drain-restart and failure-restart, so config changes in the
        factory roll out via ``rolling_restart``.
    num_replicas: pool width.
    auto_restart: rebuild dead replicas in the background. Off by
        default so tests (and capacity accounting) see deterministic
        pool shapes; deployments turn it on.
    max_resubmits: per-request cap on death-triggered resubmissions
        (default ``num_replicas``): a request that outlives that many
        replicas fails typed instead of looping.
    restart_backoff_s / restart_backoff_max_s: exponential backoff
        between auto-restarts of a dying replica (base doubles per
        death, capped). Without it a crash-looping factory rebuilds
        hot in a tight loop.
    max_restarts: per-replica death cap; once exceeded the replica
        parks in ``DEGRADED`` instead of rebuilding, and a pool with
        no healthy replicas left raises typed ``PoolDegraded``.
        ``None`` = unlimited (the pre-backoff behavior).
    seed: P2C sampling seed (deterministic tests).
    """

    def __init__(self, engine_factory: Callable[[int], Any],
                 num_replicas: int, *,
                 auto_restart: bool = False,
                 max_resubmits: Optional[int] = None,
                 max_sticky_sessions: int = 4096,
                 restart_backoff_s: float = 0.05,
                 restart_backoff_max_s: float = 5.0,
                 max_restarts: Optional[int] = 5,
                 share_prefixes: bool = False,
                 roles: Optional[Sequence[str]] = None,
                 kv_pull_deadline_s: Optional[float] = None,
                 kv_pull_backoff_s: Optional[float] = None,
                 seed: int = 0):
        if num_replicas < 1:
            raise ValueError("num_replicas must be >= 1")
        if roles is None:
            roles = [ROLE_UNIFIED] * num_replicas
        else:
            roles = list(roles)
            if len(roles) != num_replicas:
                raise ValueError(
                    f"roles must name every replica: got "
                    f"{len(roles)} roles for {num_replicas} replicas")
            for role in roles:
                if role not in REPLICA_ROLES:
                    raise ValueError(
                        f"unknown replica role {role!r}; expected "
                        f"one of {sorted(REPLICA_ROLES)}")
            if (any(r != ROLE_UNIFIED for r in roles)
                    and not share_prefixes):
                # the handoff path IS the share_prefixes KV wiring;
                # a disaggregated pool without it would re-prefill
                # every handed-off stream from scratch
                raise ValueError(
                    "role-disaggregated pools require "
                    "share_prefixes=True (the KV handoff path)")
        self._factory = engine_factory
        # Requester-side KV pull knob overrides (None = pull_prefix
        # defaults), validated typed here at construction
        self._kv_pull_knobs = kv_migration.validate_pull_knobs(
            kv_pull_deadline_s, kv_pull_backoff_s)
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._auto_restart = auto_restart
        self.max_resubmits = (max_resubmits if max_resubmits
                              is not None else num_replicas)
        self._max_sticky = max_sticky_sessions
        self.restart_backoff_s = max(0.0, float(restart_backoff_s))
        self.restart_backoff_max_s = max(
            self.restart_backoff_s, float(restart_backoff_max_s))
        self.max_restarts = max_restarts
        # installed by an attached PoolAutoscaler: returns the ETA (s)
        # until in-flight provisioned capacity joins the pool, so an
        # all-shed Retry-After never invites a client back BEFORE the
        # capacity that would serve it exists
        self.capacity_hint_fn: Optional[Callable[[], float]] = None
        self._autoscaler = None      # attached PoolAutoscaler, if any
        self._watchdog = None        # attached PoolWatchdog, if any
        self._sticky: "collections.OrderedDict[str, int]" = \
            collections.OrderedDict()
        # pool-level routing/lifecycle counters (the engines keep
        # their own ``stats``; ``EnginePool.stats`` aggregates those)
        self.route_stats: Dict[str, int] = collections.Counter()
        # typed pool event log (serve/obs.py): routing decisions,
        # resubmits, drains, SUSPECT/WEDGED transitions, replica
        # deaths/restarts, autoscaler decisions — one ring per pool,
        # merged with engine rings by the trace exporter
        self.events = obs.EventLog(2048, name="pool")
        self._stopped = False
        # Global prefix cache (share_prefixes=True): per-replica KV
        # donors so a route landing on a cold replica PULLS the hot
        # prefix's pages from the replica that already holds them
        # instead of recomputing. Donors go through
        # ``kv_migration.loopback_call`` — the JSON+b64 wire toll is
        # paid even in-process, so the pool and the fleet share one
        # transfer contract.
        self._share_prefixes = bool(share_prefixes)
        self._kv_donors: Dict[int, kv_migration.KVDonor] = {}
        # role -> RolePoolView, registered by the views themselves:
        # per-role autoscaler attachment points + pool_stats blocks
        self._role_views: Dict[str, Any] = {}
        # Current-weights source (live rollout, serve/weight_rollout):
        # the factory closes over the ORIGINAL params, so without this
        # a replica rebuilt after a mid-rollout death would rejoin the
        # fleet on stale weights. ``set_weight_source`` records the
        # payload every rebuild/add must be re-stamped to.
        self._weight_source: Optional[Dict[str, Any]] = None
        self._replicas: List[_Replica] = []
        for i in range(num_replicas):
            eng = engine_factory(i)
            self._stamp_role(eng, roles[i])
            self._stamp_replica_tag(eng, i)
            eng.start()
            rep = _Replica(i, eng, role=roles[i])
            self._replicas.append(rep)
            self._wire_kv(rep)

    @staticmethod
    def _stamp_role(engine, role: str) -> None:
        """Stamp a replica's role onto its engine AFTER the factory
        built it — one ``f(idx)`` factory serves both pools, and the
        role only steers dynamic decisions (planner caps via
        ``role_plan_caps``, load_report stamp). Engines without the
        attribute (test fakes) are left alone: routing treats a
        missing role as unified."""
        try:
            engine.role = role
        except Exception:
            pass

    @staticmethod
    def _stamp_replica_tag(engine, idx: int) -> None:
        """Stamp the pool index onto the engine so its per-replica
        metrics (the ``serve_weight_generation`` gauge) are
        attributable. Same best-effort contract as ``_stamp_role``."""
        try:
            engine.replica_tag = str(idx)
        except Exception:
            pass

    def _restamp_weights(self, rep: _Replica) -> None:
        """Bring a freshly built replica onto the pool's CURRENT
        weights. The engine factory closes over the original params;
        when a rollout has moved the fleet past them, a rebuilt or
        added replica must not rejoin on generation 0 — that is the
        kill-mid-swap hole. Best-effort: a failure leaves the replica
        serving factory weights and is evented (the rollout
        controller's convergence check will see the lagging
        weights_id)."""
        src = self._weight_source
        eng = rep.engine
        if src is None or not hasattr(eng, "swap_weights"):
            return
        try:
            eng.swap_weights(src["params"],
                             generation=src["generation"],
                             weights_id=src["weights_id"])
            self.events.append("weight_restamp", sid=rep.idx,
                               data={"generation": src["generation"],
                                     "weights_id": src["weights_id"]})
        except Exception as e:  # noqa: BLE001
            self.events.append("weight_restamp_failed", sid=rep.idx,
                               data={"error": repr(e)})

    def set_weight_source(self, params, *, weights_id: str,
                          generation: int) -> None:
        """Record the payload every future rebuild/add re-stamps to
        (``None``-free contract: call after each completed rollout or
        rollback so replica churn converges on the fleet's current
        weights, not the factory's)."""
        with self._lock:
            self._weight_source = {"params": params,
                                   "weights_id": weights_id,
                                   "generation": int(generation)}
        self.events.append("weight_source", data={
            "generation": int(generation), "weights_id": weights_id})

    def swap_replica_weights(self, idx: int, params, *,
                             weights_id: Optional[str] = None,
                             generation: Optional[int] = None,
                             mode: str = "preempt") -> int:
        """Hot-swap ONE replica's weights through the engine's
        generation fence (``LLMEngine.swap_weights``). The staged
        rollout controller drives canary waves through this. Returns
        the generation now serving on that replica."""
        with self._lock:
            rep = self._replicas[idx]
            if rep.state not in (HEALTHY, SUSPECT):
                raise RuntimeError(
                    f"replica {idx} is {rep.state}; only live "
                    f"replicas can swap weights")
        gen = rep.engine.swap_weights(params, generation=generation,
                                      weights_id=weights_id,
                                      mode=mode)
        with self._lock:
            self.route_stats["weight_swaps"] += 1
        self.events.append("weight_swap", sid=idx,
                           data={"generation": gen,
                                 "weights_id": rep.engine.weights_id,
                                 "mode": mode})
        return gen

    # --------------------------------------------------------- public

    @property
    def n_replicas(self) -> int:
        return len(self._replicas)

    def engines(self) -> List[Any]:
        """Every replica engine, regardless of state (quiescence
        checks cover dead replicas too — a crash must not leak)."""
        return [r.engine for r in self._replicas]

    def replica(self, idx: int) -> _Replica:
        return self._replicas[idx]

    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas
                       if r.state == HEALTHY)

    def active_count(self) -> int:
        """Replicas currently holding capacity (anything but a
        scale-down tombstone) — the autoscaler's notion of pool
        size, and the bench's chip-count at any instant."""
        with self._lock:
            return sum(1 for r in self._replicas
                       if r.state != RETIRED)

    @property
    def degraded(self) -> bool:
        """True when any replica burned through its restart cap."""
        with self._lock:
            return any(r.state == DEGRADED for r in self._replicas)

    def disaggregated(self) -> bool:
        """True while a healthy prefill-role replica exists — the
        condition under which new online requests split into the
        two-leg prefill -> decode service. Recomputed per submit on
        purpose: when the last prefill replica dies, the pool
        degrades to unified service instead of stranding traffic."""
        with self._lock:
            return any(r.role == ROLE_PREFILL and r.state == HEALTHY
                       for r in self._replicas)

    def role_counts(self) -> Dict[str, int]:
        """Active (non-retired) replica count per role."""
        out: Dict[str, int] = collections.Counter()
        with self._lock:
            for r in self._replicas:
                if r.state != RETIRED:
                    out[r.role] += 1
        return dict(out)

    def submit(self, prompt_ids: Sequence[int],
               max_new_tokens: int = 64,
               deadline_s: Optional[float] = None,
               session_id: Optional[str] = None,
               trace_id: Optional[str] = None,
               priority: str = LANE_ONLINE) -> PoolRequestHandle:
        """Route and queue one request (engine ``submit`` signature
        plus ``session_id`` for stickiness and ``trace_id`` for
        request-scope tracing — the id survives replica-death
        resubmits because the handle re-sends it). Raises exactly
        like a single engine: validation ``RequestError``
        immediately, pool-aggregate ``EngineOverloaded`` when every
        healthy replica sheds, ``EngineShutdown`` when none is
        left.

        ``priority=LANE_BATCH`` routes through the batch spill path:
        least batch-backlog replica, skipping session stickiness and
        prefix affinity entirely — batch work soaks whatever replica
        is emptiest and NEVER claims (or pollutes) the sticky/affinity
        placement online traffic depends on. The lane rides replica-
        death resubmits unchanged."""
        if self._stopped:
            raise EngineShutdown("engine pool stopped")
        prompt = [int(t) for t in prompt_ids]
        handle = PoolRequestHandle(self, prompt, max_new_tokens,
                                   deadline_s, session_id, trace_id,
                                   priority=priority)
        if (priority == LANE_ONLINE and max_new_tokens > 1
                and self.disaggregated()):
            # Two-leg disaggregated service: leg 1 takes ONE token
            # on the prefill side (session stickiness deliberately
            # unused — a sticky entry must never pin a session to a
            # prefill replica). If the prefill side cannot admit at
            # all, serve unified below — disaggregation degrades,
            # availability doesn't.
            try:
                rep, inner = self._submit_once(
                    prompt, 1, deadline_s, None, trace_id=trace_id,
                    roles=_PREFILL_SIDE)
                handle._disagg = True
                handle._attach(rep, inner)
                return handle
            except (EngineShutdown, PoolDegraded):
                pass
        rep, inner = self._submit_once(prompt, max_new_tokens,
                                       deadline_s, session_id,
                                       trace_id=trace_id,
                                       priority=priority)
        handle._attach(rep, inner)
        return handle

    def submit_rollout_batch(self, prompts: Sequence[Sequence[int]],
                             max_new_tokens: int = 64,
                             deadline_s: Optional[float] = None,
                             trace_id: Optional[str] = None
                             ) -> List[PoolRequestHandle]:
        """Rollout-batch submit surface (ray_tpu/rl): one BATCH-lane
        request per prompt, routed through the batch spill path
        (least-backlog replica, no stickiness/affinity claims), in
        order. Mirrors ``LLMEngine.submit_rollout_batch`` so the RL
        generator drives a single engine and a pool through one
        interface; per-token logprobs ride the handles when the
        replica engines were built with ``capture_logprobs=True``."""
        return [self.submit(list(p), max_new_tokens=max_new_tokens,
                            deadline_s=deadline_s,
                            trace_id=(f"{trace_id}:{i}"
                                      if trace_id else None),
                            priority=LANE_BATCH)
                for i, p in enumerate(prompts)]

    def _submit_leg(self, prompt: List[int], max_new_tokens: int,
                    deadline_s: Optional[float],
                    session_id: Optional[str], *,
                    trace_id: Optional[str] = None,
                    roles: Optional[Sequence[str]] = None,
                    pull: Optional[Dict[str, Any]] = None,
                    exclude: Optional[set] = None,
                    fallback_any: bool = False):
        """One leg of a disaggregated request: a role-filtered
        ``_submit_once``, optionally degrading to an unrestricted
        route when the whole role side is gone (leg-1 resubmits —
        a dead prefill pool must not strand a request a decode
        replica could still serve, slowly, via plain prefill)."""
        try:
            return self._submit_once(prompt, max_new_tokens,
                                     deadline_s, session_id,
                                     trace_id=trace_id, roles=roles,
                                     pull=pull, exclude=exclude)
        except (EngineShutdown, PoolDegraded):
            if not fallback_any:
                raise
            return self._submit_once(prompt, max_new_tokens,
                                     deadline_s, session_id,
                                     trace_id=trace_id)

    # -------------------------------------------- handoff bookkeeping

    def _note_handoff(self, donor: Optional[_Replica],
                      target: _Replica,
                      trace_id: Optional[str] = None) -> None:
        with self._lock:
            self.route_stats["disagg_handoffs"] += 1
        self.events.append(
            "handoff", sid=target.idx,
            data={"from": donor.idx if donor is not None else None,
                  "to": target.idx, "trace_id": trace_id})
        _metrics()["disagg_handoffs"].inc()

    def _note_handoff_first_token(self, target: _Replica,
                                  trace_id: Optional[str] = None
                                  ) -> None:
        """First decode token on the new replica — the closing edge
        of the handoff-latency interval tools/trace_report.py
        derives (prefill-done is the ``handoff`` event above)."""
        self.events.append("handoff_first_token", sid=target.idx,
                           data={"to": target.idx,
                                 "trace_id": trace_id})

    def _note_handoff_fallback(self, donor: Optional[_Replica],
                               cause: BaseException,
                               trace_id: Optional[str] = None
                               ) -> None:
        with self._lock:
            self.route_stats["disagg_handoff_fallbacks"] += 1
        self.events.append(
            "handoff_fallback",
            sid=donor.idx if donor is not None else None,
            data={"error": repr(cause), "trace_id": trace_id})
        _metrics()["disagg_fallbacks"].inc()

    def shutdown(self) -> None:
        """Stop every replica; queued/in-flight requests fail typed
        ``EngineShutdown`` (per-engine contract). Idempotent."""
        self._stopped = True
        for rep in self._replicas:
            try:
                rep.engine.shutdown()
            except Exception:
                pass
            rep.state = DEAD

    # ------------------------------------------------------- lifecycle

    def drain(self, idx: int, timeout_s: float = 30.0) -> bool:
        """Gracefully restart replica ``idx``: stop admitting, let
        in-flight work finish (up to ``timeout_s``), shut down, and
        rebuild from the factory. Returns True when the drain
        completed with no work left (nobody failed); False when the
        budget expired and stragglers were axed — those fail typed
        and unstreamed ones recover via resubmission, so the restart
        still converges."""
        clean = self._drain_out(idx, timeout_s)
        self._rebuild(idx)
        return clean

    def _drain_out(self, idx: int, timeout_s: float) -> bool:
        """The health-gated half of a drain: stop admitting, wait for
        in-flight work (bounded), shut down. Shared by ``drain``
        (which rebuilds after) and ``retire`` (which doesn't)."""
        with self._lock:
            rep = self._replicas[idx]
            if rep.state != HEALTHY:
                raise RuntimeError(
                    f"replica {idx} is {rep.state}; only a healthy "
                    f"replica can drain")
            rep.state = DRAINING
            self.route_stats["drains"] += 1
            self._drop_sticky_locked(idx)
        self.events.append("drain", sid=idx)
        _metrics()["drains"].inc()
        eng = rep.engine
        eng.drain()
        clean = eng.wait_idle(timeout_s)
        try:
            eng.shutdown()
        except Exception:
            pass
        return clean

    # -------------------------------------------------------- scaling

    def add_replica(self, role: str = ROLE_UNIFIED) -> int:
        """Scale up by one: build a fresh engine from the factory,
        reusing a retired slot index when one exists (its generation
        bumps) or appending a new one. ``role`` places the new
        capacity in a disaggregated pool's prefill or decode side
        (default unified). Returns the replica index."""
        if self._stopped:
            raise EngineShutdown("engine pool stopped")
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"unknown replica role {role!r}; expected one of "
                f"{sorted(REPLICA_ROLES)}")
        with self._lock:
            retired = [r for r in self._replicas
                       if r.state == RETIRED]
            idx = retired[0].idx if retired else len(self._replicas)
            if retired:
                retired[0].role = role  # _rebuild carries it over
        if retired:
            self._rebuild(idx)
        else:
            eng = self._factory(idx)
            self._stamp_role(eng, role)
            self._stamp_replica_tag(eng, idx)
            eng.start()
            rep = _Replica(idx, eng, role=role)
            with self._lock:
                self._replicas.append(rep)
            self._wire_kv(rep)
            self._restamp_weights(rep)
        with self._lock:
            self.route_stats["replicas_added"] += 1
        return idx

    def retire(self, idx: int, timeout_s: float = 30.0) -> bool:
        """Scale down replica ``idx`` through the SAME health-gated
        drain path as a rolling restart — admit nothing new, finish
        in-flight work, shut down — but leave a ``RETIRED`` tombstone
        instead of rebuilding. In-flight requests either complete
        normally (clean drain) or fail typed / resubmit under the
        at-most-once rule (budget expired), exactly like ``drain``.
        Returns the drain's cleanliness."""
        with self._lock:
            healthy = sum(1 for r in self._replicas
                          if r.state == HEALTHY)
            if healthy <= 1 and self._replicas[idx].state == HEALTHY:
                raise RuntimeError(
                    "refusing to retire the last healthy replica")
        clean = self._drain_out(idx, timeout_s)
        with self._lock:
            self._replicas[idx].state = RETIRED
            self.route_stats["replicas_retired"] += 1
        return clean

    def scale_down(self, n: int = 1, timeout_s: float = 30.0,
                   role: Optional[str] = None) -> List[int]:
        """Retire the ``n`` least-loaded healthy replicas (by
        outstanding tokens), never going below one healthy replica —
        per ROLE when ``role`` is given (a per-role autoscaler must
        never retire its side's last replica, even when the other
        side has plenty). Returns the retired indices."""
        with self._lock:
            candidates = [r for r in self._replicas
                          if r.state == HEALTHY
                          and (role is None or r.role == role)]
        n = min(n, len(candidates) - 1)
        if n <= 0:
            return []
        load = []
        for r in candidates:
            try:
                rpt = r.engine.load_report()
                load.append((rpt.get("outstanding_tokens", 0), r.idx))
            except Exception:
                load.append((0, r.idx))
        load.sort()
        out = []
        for _, idx in load[:n]:
            try:
                self.retire(idx, timeout_s)
            except RuntimeError:
                continue       # raced a death; replica count moved
            out.append(idx)
        return out

    def scale_to(self, n: int, timeout_s: float = 30.0) -> int:
        """Converge the pool to ``n`` active replicas (adds via the
        factory, removes via ``scale_down``'s drain path). Returns
        the resulting active count."""
        if n < 1:
            raise ValueError("scale_to target must be >= 1")
        while self.active_count() < n:
            self.add_replica()
        excess = self.active_count() - n
        if excess > 0:
            self.scale_down(excess, timeout_s)
        return self.active_count()

    def rolling_restart(self, timeout_s: float = 30.0) -> bool:
        """Drain-restart every replica in sequence (a config rollout
        when the factory closes over new knobs). True iff every
        drain was clean."""
        clean = True
        for idx in range(len(self._replicas)):
            clean = self.drain(idx, timeout_s) and clean
        return clean

    def restart_dead(self) -> int:
        """Rebuild every DEAD (and crash-loop DEGRADED — this is the
        manual override) replica now. Returns how many were
        rebuilt."""
        with self._lock:
            dead = [r.idx for r in self._replicas
                    if r.state in (DEAD, DEGRADED)]
        for idx in dead:
            self._rebuild(idx)
        return len(dead)

    def _rebuild(self, idx: int) -> None:
        old = self._replicas[idx]
        eng = self._factory(idx)
        self._stamp_role(eng, old.role)
        self._stamp_replica_tag(eng, idx)
        eng.start()
        with self._lock:
            self._replicas[idx] = _Replica(
                idx, eng, HEALTHY, deaths=old.deaths,
                generation=old.generation + 1, role=old.role)
            self.route_stats["restarts"] += 1
        self._wire_kv(self._replicas[idx])
        # kill-mid-swap closure: the factory built the engine on the
        # ORIGINAL params; converge it onto the pool's current weights
        self._restamp_weights(self._replicas[idx])
        self.events.append("restart", sid=idx,
                           data={"generation": old.generation + 1})
        _metrics()["restarts"].inc()

    # -------------------------------------------------- watchdog hooks

    def mark_suspect(self, rep: _Replica) -> bool:
        """HEALTHY -> SUSPECT (watchdog quarantine). The replica
        immediately stops counting as capacity everywhere — routing,
        ``healthy_count``, scale-down candidacy, autoscaler signals —
        because they all filter on HEALTHY. Returns False when the
        replica moved on (died, drained, replaced) since observed."""
        with self._lock:
            if (self._replicas[rep.idx] is not rep
                    or rep.state != HEALTHY):
                return False
            rep.state = SUSPECT
            self.route_stats["suspects"] += 1
            self._drop_sticky_locked(rep.idx)
        self.events.append("suspect", sid=rep.idx)
        _metrics()["suspects"].inc()
        return True

    def clear_suspect(self, rep: _Replica) -> bool:
        """SUSPECT -> HEALTHY: the probe saw progress (heartbeat
        advanced or work drained) — a long-but-moving dispatch, not a
        wedge. The replica resumes taking traffic."""
        with self._lock:
            if (self._replicas[rep.idx] is not rep
                    or rep.state != SUSPECT):
                return False
            rep.state = HEALTHY
        self.events.append("suspect_cleared", sid=rep.idx)
        return True

    def mark_wedged(self, rep: _Replica,
                    err: Optional[BaseException] = None,
                    stalled_for_s: Optional[float] = None) -> bool:
        """Declare a silent replica WEDGED and drive the EXISTING
        death path: ``force_kill`` the engine out-of-band (lock-free —
        the wedged scheduler thread holds the engine lock), which
        unblocks every consumer typed so unstreamed requests resubmit
        token-identically, then ``_note_replica_death`` marks it DEAD,
        counts the death, and schedules the backoff rebuild with a
        generation bump. Healthy replicas are never touched."""
        with self._lock:
            if (self._replicas[rep.idx] is not rep
                    or rep.state not in (HEALTHY, SUSPECT)):
                return False
            self.route_stats["wedged"] += 1
        self.events.append("wedged", sid=rep.idx,
                           data={"stalled_for_s": stalled_for_s,
                                 "error": repr(err) if err else None})
        m = _metrics()
        m["wedged"].inc()
        if stalled_for_s is not None:
            m["wedge_latency"].observe(stalled_for_s)
        try:
            rep.engine.force_kill(err)
        except Exception:
            pass
        return self._note_replica_death(rep)

    def _note_replica_death(self, rep: _Replica) -> bool:
        """Judge (and record) a replica death. True iff ``rep``'s
        engine has globally stopped — the discriminator between
        request-level failures (engine alive; not the pool's
        business) and replica-level ones (recoverable by routing
        around the corpse)."""
        if not getattr(rep.engine, "_stopped", False):
            return False
        restart = False
        transitioned = False
        with self._lock:
            if (self._replicas[rep.idx] is rep
                    and rep.state not in (DEAD, DEGRADED, RETIRED)):
                rep.state = DEAD
                rep.deaths += 1
                transitioned = True
                self.route_stats["replica_deaths"] += 1
                self._drop_sticky_locked(rep.idx)
                restart = self._auto_restart and not self._stopped
                if (restart and self.max_restarts is not None
                        and rep.deaths > self.max_restarts):
                    # crash loop: stop feeding the factory — park the
                    # replica DEGRADED until a human (or restart_dead)
                    # intervenes
                    restart = False
                    rep.state = DEGRADED
                    self.route_stats["crash_loops"] += 1
        if transitioned:
            self.events.append("replica_death", sid=rep.idx,
                               data={"deaths": rep.deaths,
                                     "state": rep.state})
            _metrics()["replica_deaths"].inc()
        # idempotent: unblocks every remaining consumer typed and
        # frees whatever the dead scheduler left behind
        try:
            rep.engine.shutdown()
        except Exception:
            pass
        if restart:
            # exponential backoff before the rebuild: first death
            # restarts after backoff_s, each further death doubles it
            # (capped), so a crash-looping factory cannot spin hot
            backoff = min(self.restart_backoff_max_s,
                          self.restart_backoff_s
                          * (2 ** (rep.deaths - 1)))
            threading.Thread(target=self._backoff_rebuild,
                             args=(rep, backoff),
                             name=f"pool-restart-{rep.idx}",
                             daemon=True).start()
        return True

    def _backoff_rebuild(self, rep: _Replica, backoff_s: float
                         ) -> None:
        if backoff_s > 0:
            time.sleep(backoff_s)
        with self._lock:
            # the world may have moved during the backoff: pool
            # stopped, replica replaced, or manually rebuilt already
            if (self._stopped or self._replicas[rep.idx] is not rep
                    or rep.state != DEAD):
                return
        self._rebuild(rep.idx)

    def _restart_eta_s(self) -> float:
        """Honest Retry-After for a pool with no healthy replica: the
        max of any in-flight provisioning ETA (autoscaler hint) and
        the longest pending auto-restart backoff — the soonest moment
        a retry could plausibly find capacity."""
        eta = 0.0
        if self.capacity_hint_fn is not None:
            try:
                eta = max(eta, float(self.capacity_hint_fn()))
            except Exception:
                # a raising provider hint must not poison the ETA:
                # fall back to the pending-backoff estimate below
                _metrics()["capacity_hint_errors"].inc()
        return max(eta, self._pending_backoff_eta_s())

    def _pending_backoff_eta_s(self) -> float:
        """Longest pending auto-restart backoff — the capacity ETA
        the pool can always compute from its own state, used as the
        fallback whenever ``capacity_hint_fn`` raises."""
        eta = 0.0
        if self._auto_restart:
            with self._lock:
                dead_deaths = [r.deaths for r in self._replicas
                               if r.state == DEAD]
            for deaths in dead_deaths:
                eta = max(eta, min(
                    self.restart_backoff_max_s,
                    self.restart_backoff_s
                    * (2 ** max(0, deaths - 1))))
        return eta

    def _drop_sticky_locked(self, idx: int) -> None:
        for k in [k for k, v in self._sticky.items() if v == idx]:
            del self._sticky[k]

    def _count_requeue(self, trace_id: Optional[str] = None) -> None:
        with self._lock:
            self.route_stats["requeues"] += 1
        self.events.append("resubmit",
                           data={"trace_id": trace_id}
                           if trace_id is not None else None)
        _metrics()["requeues"].inc()

    # ---------------------------------------------- prefix sharing

    def _wire_kv(self, rep: _Replica) -> None:
        """Register ``rep``'s engine as a KV donor and hand it a
        fetcher that pulls from its siblings. Re-run on every
        rebuild: the donor table must always point at the LIVE
        engine for each slot (a transfer begun against the old
        incarnation aborts typed on the fresh donor's empty
        table)."""
        if not self._share_prefixes:
            return
        eng = rep.engine
        if not hasattr(eng, "kv_migration_stats"):
            return
        with self._lock:
            self._kv_donors[rep.idx] = kv_migration.KVDonor(eng)
        eng.kv_fetcher = lambda pull, e=eng: self._kv_fetch(e, pull)

    def _kv_fetch(self, requester_engine,
                  pull: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        with self._lock:
            donor = self._kv_donors.get(pull.get("replica_idx"))
        if donor is None:
            return None
        try:
            return kv_migration.pull_prefix(
                kv_migration.loopback_call(donor),
                pull.get("hashes") or [],
                stats=requester_engine.kv_migration_stats,
                **self._kv_pull_knobs)
        except Exception:
            return None

    def _pull_hint(self, prompt: List[int], rep: _Replica,
                   reports: Dict[int, Dict[str, Any]]
                   ) -> Optional[Dict[str, Any]]:
        """When a sibling replica advertises a strictly longer
        cached prefix of this prompt than the routed target does,
        name it as the donor — the target pulls instead of
        recomputing. A hint only: any staleness degrades to plain
        prefill on the target."""
        Pg = getattr(rep.engine, "Pg", 0)
        if Pg <= 0 or len(prompt) < Pg:
            return None
        chain = path_hashes(prompt, Pg)
        # weight-generation fence, cross-replica half: a donor serving
        # a DIFFERENT weight payload holds KV computed under weights
        # the target does not run — matching it would decode new
        # tokens against foreign-generation pages. Mid-rollout, pulls
        # simply stay within each side of the fleet.
        my_wid = reports.get(rep.idx, {}).get("weights_id")

        def cover(idx: int) -> int:
            rpt = reports.get(idx, {})
            if rpt.get("weights_id") != my_wid:
                return 0
            have = rpt.get("prefix_digest") or frozenset()
            n = 0
            for h in chain:
                if h not in have:
                    break
                n += 1
            return n

        best_idx, best_n = None, cover(rep.idx)
        for idx in reports:
            if idx == rep.idx:
                continue
            n = cover(idx)
            if n > best_n:
                best_idx, best_n = idx, n
        if best_idx is None:
            return None
        with self._lock:
            self.route_stats["pull_hints"] += 1
        return {"hashes": chain[:best_n], "replica_idx": best_idx}

    def kv_migration_stats(self) -> Optional[Dict[str, Any]]:
        """Summed cross-replica KV migration counters (pulls, pages,
        wire bytes, aborts, fallbacks) — the ``kv_migration`` block
        in pool stats, bench artifacts, and flight bundles."""
        per = [getattr(r.engine, "kv_migration_stats", None)
               for r in self._replicas]
        return self._agg_numeric(per)

    # --------------------------------------------------------- routing

    def _submit_once(self, prompt: List[int], max_new_tokens: int,
                     deadline_s: Optional[float],
                     session_id: Optional[str],
                     trace_id: Optional[str] = None,
                     priority: str = LANE_ONLINE,
                     roles: Optional[Sequence[str]] = None,
                     pull: Optional[Dict[str, Any]] = None,
                     exclude: Optional[set] = None,
                     target_idx: Optional[int] = None,
                     record_sticky: bool = True):
        """Route + submit until one replica accepts. Replicas that
        shed/die/drain between the snapshot and the submit are
        excluded and routing retries; when nothing accepts, the
        failure is typed and aggregated (module docstring).

        Disaggregation extras: ``roles`` restricts routing to those
        replica roles; ``pull`` attaches an explicit KV pull hint
        (the finished-prefill push hint) overriding the routed one;
        ``target_idx`` bypasses routing entirely and submits to ONE
        named healthy replica (the decode-in-place fallback);
        ``record_sticky=False`` keeps a route from writing session
        placement state."""
        batch = priority == LANE_BATCH
        exclude = set(exclude) if exclude else set()
        shed: List[EngineOverloaded] = []
        while True:
            if target_idx is not None:
                rep, decision = self._route_direct(target_idx)
            else:
                rep, decision = self._route(prompt, session_id,
                                            exclude, batch=batch,
                                            roles=roles)
            if rep is not None and pull is not None:
                decision = dict(decision, pull=pull)
            if rep is None:
                hints = decision.get("hints", [])
                hints += [e.retry_after_s for e in shed]
                if hints:
                    with self._lock:
                        self.route_stats["all_shed"] += 1
                    _metrics()["all_shed"].inc()
                    # Retry-After honesty under autoscaling: when
                    # capacity is already provisioning, the hint must
                    # cover its remaining ETA — never invite a client
                    # back before a replica exists to serve it
                    if self.capacity_hint_fn is not None:
                        try:
                            eta = float(self.capacity_hint_fn())
                        except Exception:
                            # broken hint provider: fall back to the
                            # pool's own pending-backoff ETA rather
                            # than silently dropping the signal
                            _metrics()["capacity_hint_errors"].inc()
                            eta = self._pending_backoff_eta_s()
                        if eta > 0:
                            hints.append(eta)
                    err = EngineOverloaded(
                        f"all healthy replicas shed (retry hints "
                        f"{sorted(set(round(h, 3) for h in hints))})",
                        retry_after_s=max(hints))
                    if shed:
                        raise err from shed[-1]
                    raise err
                # No healthy replica and nobody shed: a bare 503
                # would tell the client nothing — attach the honest
                # restart/provisioning ETA so the proxy can emit
                # Retry-After on the degraded path too.
                eta = self._restart_eta_s()
                if self.degraded:
                    raise PoolDegraded(
                        "no healthy replicas: the pool burned through "
                        "its crash-loop restart budget "
                        f"(max_restarts={self.max_restarts})",
                        retry_after_s=eta if eta > 0 else None)
                err = EngineShutdown("no healthy replicas in pool")
                if eta > 0:
                    err.retry_after_s = eta
                raise err
            try:
                # trace_id only when set: fake engines in tests (and
                # older engine builds) take the bare 3-arg signature
                kw: Dict[str, Any] = dict(
                    max_new_tokens=max_new_tokens,
                    deadline_s=deadline_s)
                if trace_id is not None:
                    kw["trace_id"] = trace_id
                if decision.get("pull") is not None:
                    kw["pull"] = decision["pull"]
                if batch:
                    # only when non-default: fake engines in tests
                    # (and older builds) lack the priority kwarg
                    kw["priority"] = priority
                inner = rep.engine.submit(prompt, **kw)
            except EngineOverloaded as e:
                if target_idx is not None:
                    raise       # the named target shed: no retry loop
                shed.append(e)
                exclude.add(rep.idx)
                continue
            except (EngineShutdown, EngineDraining) as e:
                # raced a death/drain after the snapshot
                self._note_replica_death(rep)
                if target_idx is not None:
                    raise
                exclude.add(rep.idx)
                continue
            self._record_route(rep, decision,
                               session_id if record_sticky else None,
                               trace_id=trace_id)
            return rep, inner

    def _route_direct(self, idx: int):
        """Directly target replica ``idx`` (decode-in-place
        fallback): no routing policy, no sticky write — just a
        health check shaped like a route decision."""
        with self._lock:
            rep = (self._replicas[idx]
                   if 0 <= idx < len(self._replicas) else None)
            if rep is None or rep.state != HEALTHY:
                rep = None
        if rep is None:
            raise EngineShutdown(
                f"replica {idx} is not healthy; cannot decode in "
                f"place")
        return rep, {"kind": "direct", "pages": 0}

    def _route(self, prompt: List[int], session_id: Optional[str],
               exclude: set, *, batch: bool = False,
               roles: Optional[Sequence[str]] = None):
        """Pick a replica (or ``(None, {"hints": [...]})`` when none
        can admit). Lock discipline: the replica table is read under
        the pool lock; ``load_report()`` calls happen OUTSIDE it (they
        briefly take each engine's lock).

        ``batch=True`` bypasses the sticky -> affinity -> P2C policy
        entirely: the batch lane routes to the replica with the least
        batch backlog (ties on outstanding tokens), reads — never
        writes — placement state, and respects each replica's
        ``max_queued_batch`` bound. Batch never lands on a
        prefill-only replica: backlog spills only into the
        decode/unified pool, whose admission knobs can actually run
        long decode streams.

        ``roles`` (disaggregation) restricts candidates to those
        replica roles."""
        with self._lock:
            reps = [r for r in self._replicas
                    if r.state == HEALTHY and r.idx not in exclude
                    and (roles is None or r.role in roles)
                    and not (batch and r.role == ROLE_PREFILL)]
            sticky_idx = (self._sticky.get(session_id)
                          if session_id is not None else None)
            if sticky_idx is not None:
                srep = (self._replicas[sticky_idx]
                        if sticky_idx < len(self._replicas) else None)
                if srep is not None and srep.role == ROLE_PREFILL:
                    # A sticky entry must never pin a session to a
                    # prefill-only replica (e.g. written before the
                    # replica was re-roled): drop it, don't follow it.
                    del self._sticky[session_id]
                    sticky_idx = None
        if not reps:
            return None, {"hints": []}
        reports = {r.idx: r.engine.load_report() for r in reps}
        m = _metrics()
        for r in reps:
            rep_report = reports[r.idx]
            tags = {"replica": str(r.idx)}
            m["free_slots"].set(rep_report["free_slots"], tags=tags)
            m["queue_depth"].set(rep_report["queue_depth"],
                                 tags=tags)
            m["batch_queue_depth"].set(
                rep_report.get("queue_depth_batch", 0), tags=tags)
        # A replica can die while IDLE — engine thread gone with no
        # in-flight handle around to trip the death path. Routing is
        # the other place a corpse becomes visible: note the death
        # here so auto-restart/crash-loop accounting fires instead of
        # the replica sitting "healthy" in the table forever while
        # every route skips it.
        for r in reps:
            if reports[r.idx]["stopped"]:
                self._note_replica_death(r)
        # selection itself is the shared fleet.routing core: the same
        # sticky -> affinity/spill -> P2C policy the FleetRouter runs
        # over the directory's advertised reports
        by_key = {r.idx: r for r in reps}
        live = [r for r in reps
                if not reports[r.idx]["stopped"]
                and not reports[r.idx]["draining"]]
        if batch:
            return self._route_batch(live, reports)
        cands = [Candidate(r.idx, reports[r.idx],
                           getattr(r.engine, "Pg", 0))
                 for r in live]
        pick, decision = select_candidate(
            cands, prompt, sticky_key=sticky_idx, rng=self._rng)
        if pick is None:
            return None, decision
        rep = by_key[pick.key]
        if self._share_prefixes:
            hint = self._pull_hint(prompt, rep, reports)
            if hint is not None:
                decision = dict(decision, pull=hint)
        return rep, decision

    def _route_batch(self, live: List[_Replica],
                     reports: Dict[int, Dict[str, Any]]):
        """Batch-lane spill routing: least batch backlog first, ties
        on least outstanding token work — the lane flows wherever
        capacity is idlest. Replicas whose batch lane is at its
        ``max_queued_batch`` bound contribute a retry hint instead of
        a queue position; when every replica is bound, the caller
        aggregates those hints into one pool-level shed. Sticky and
        affinity state is untouched: batch never claims a placement
        online traffic could want."""
        hints: List[float] = []
        open_reps: List[_Replica] = []
        for r in live:
            rpt = reports[r.idx]
            bound = rpt.get("max_queued_batch")
            if (bound is not None
                    and rpt.get("queue_depth_batch", 0) >= bound):
                hints.append(rpt.get("shed_retry_after_s", 1.0))
                continue
            open_reps.append(r)
        if not open_reps:
            return None, {"hints": hints}
        pick = min(open_reps,
                   key=lambda r: (
                       reports[r.idx].get("queue_depth_batch", 0),
                       reports[r.idx].get("outstanding_tokens", 0),
                       r.idx))
        return pick, {"kind": "batch", "pages": 0, "spilled": False}

    def _record_route(self, rep: _Replica, decision: Dict[str, Any],
                      session_id: Optional[str],
                      trace_id: Optional[str] = None) -> None:
        self.events.append(
            "route", sid=rep.idx,
            data={"kind": decision["kind"],
                  "pages": decision.get("pages", 0),
                  "spilled": bool(decision.get("spilled")),
                  "trace_id": trace_id})
        m = _metrics()
        with self._lock:
            self.route_stats["routed"] += 1
            self.route_stats[f"route_{decision['kind']}"] += 1
            if decision.get("pages", 0) > 0:
                # an affinity HIT is a route landing on a replica
                # that already holds >= 1 page of this prompt's
                # prefix — whichever rule picked it
                self.route_stats["affinity_hits"] += 1
                self.route_stats["affinity_hit_pages"] += \
                    decision["pages"]
            if decision["kind"] == "sticky":
                self.route_stats["sticky_hits"] += 1
            if decision.get("spilled"):
                self.route_stats["spills"] += 1
            if (session_id is not None
                    and decision["kind"] != "batch"):
                # batch routes never write placement state: a batch
                # job naming a session must not steal (or evict, via
                # the LRU bound) the sticky entry online traffic
                # relies on
                self._sticky[session_id] = rep.idx
                self._sticky.move_to_end(session_id)
                while len(self._sticky) > self._max_sticky:
                    self._sticky.popitem(last=False)
        m["routed"].inc()
        if decision.get("pages", 0) > 0:
            m["affinity_hits"].inc()
        if decision["kind"] == "sticky":
            m["sticky_hits"].inc()
        if decision.get("spilled"):
            m["spills"].inc()

    # ---------------------------------------------------- aggregation

    @property
    def stats(self) -> Dict[str, int]:
        """Summed engine counters across replicas (the single-engine
        ``stats`` surface, fleet-wide)."""
        total: Dict[str, int] = collections.Counter()
        for rep in self._replicas:
            total.update(rep.engine.stats)
        return total

    @property
    def ttfts_s(self) -> List[float]:
        out: List[float] = []
        for rep in self._replicas:
            out.extend(rep.engine.ttfts_s)
        return out

    def load_reports(self, role: Optional[str] = None
                     ) -> Dict[int, Dict[str, Any]]:
        with self._lock:
            reps = [r for r in self._replicas
                    if r.state in (HEALTHY, DRAINING)
                    and (role is None or r.role == role)]
        return {r.idx: r.engine.load_report() for r in reps}

    def load_report(self, role: Optional[str] = None
                    ) -> Dict[str, Any]:
        """Pool-aggregate load snapshot (the single-engine
        ``load_report`` surface, summed over live replicas — what the
        serve controller's replica table stores for cross-replica
        routing hints). No digest: prefix affinity is an intra-pool
        decision; the deployment-level router only needs pressure.
        ``role`` restricts the aggregate to one disaggregated side —
        the view a per-role autoscaler senses."""
        reports = list(self.load_reports(role).values())
        with self._lock:
            n = sum(1 for r in self._replicas
                    if role is None or r.role == role)
            active = sum(1 for r in self._replicas
                         if r.state != RETIRED
                         and (role is None or r.role == role))
            healthy = sum(1 for r in self._replicas
                          if r.state == HEALTHY
                          and (role is None or r.role == role))
            role_counts: Dict[str, int] = collections.Counter(
                r.role for r in self._replicas
                if r.state != RETIRED)
        agg = {"free_slots": 0, "free_pages": 0, "queue_depth": 0,
               "queue_depth_batch": 0,
               "outstanding_tokens": 0, "draining": False,
               "stopped": not reports, "max_queued": None,
               "shed_retry_after_s": 1.0,
               "total_slots": 0, "shed_total": 0,
               "ttft_ewma_s": None,
               "itl_ewma_s": None,
               "role": role if role is not None else ROLE_UNIFIED,
               "roles": dict(role_counts),
               "n_replicas": n,
               "active_replicas": active,
               "healthy_replicas": healthy,
               # 2-D scale-out stamp: tp devices per replica x
               # n_replicas slices — uniform across a pool (replicas
               # are interchangeable), so the max IS the value
               "tp": max((rpt.get("tp", 1) for rpt in reports),
                         default=1)}
        for rpt in reports:
            agg["free_slots"] += rpt["free_slots"]
            agg["free_pages"] += rpt["free_pages"]
            agg["queue_depth"] += rpt["queue_depth"]
            agg["queue_depth_batch"] += rpt.get(
                "queue_depth_batch", 0)
            agg["outstanding_tokens"] += rpt["outstanding_tokens"]
            agg["shed_retry_after_s"] = max(
                agg["shed_retry_after_s"], rpt["shed_retry_after_s"])
            agg["total_slots"] += rpt.get("total_slots", 0)
            agg["shed_total"] += rpt.get("shed_total", 0)
            # worst replica wins: the SLO is violated if ANY replica's
            # first-token latency drifted, and routing can only
            # partially steer around a slow one
            ewma = rpt.get("ttft_ewma_s")
            if ewma is not None:
                agg["ttft_ewma_s"] = ewma if agg["ttft_ewma_s"] \
                    is None else max(agg["ttft_ewma_s"], ewma)
            itl = rpt.get("itl_ewma_s")
            if itl is not None:
                agg["itl_ewma_s"] = itl if agg["itl_ewma_s"] \
                    is None else max(agg["itl_ewma_s"], itl)
        # rollout visibility: the newest generation serving anywhere
        # in the pool, and whether the fleet is mid-rollout (mixed
        # payloads across live replicas)
        agg["weight_generation"] = max(
            (rpt.get("weight_generation", 0) for rpt in reports),
            default=0)
        wids = {rpt.get("weights_id") for rpt in reports
                if rpt.get("weights_id") is not None}
        agg["weights_mixed"] = len(wids) > 1
        return agg

    def pool_stats(self) -> Dict[str, Any]:
        """Routing/lifecycle counters + per-replica snapshot — the
        pool block in serve stats and bench artifacts."""
        with self._lock:
            counters = dict(self.route_stats)
            reps = [{"idx": r.idx, "state": r.state,
                     "deaths": r.deaths,
                     "generation": r.generation,
                     "role": r.role,
                     # weight fence state (pool incarnation
                     # "generation" above is a DIFFERENT counter:
                     # restarts, not rollouts)
                     "weight_generation": getattr(
                         r.engine, "weight_generation", 0),
                     "weights_id": getattr(
                         r.engine, "weights_id", None)}
                    for r in self._replicas]
            role_views = dict(self._role_views)
        routed = counters.get("routed", 0)
        counters["affinity_hit_rate"] = round(
            counters.get("affinity_hits", 0) / routed, 4) \
            if routed else 0.0
        counters["spill_rate"] = round(
            counters.get("spills", 0) / routed, 4) if routed else 0.0
        counters["n_replicas"] = len(reps)
        counters["active_replicas"] = sum(
            1 for r in reps if r["state"] != RETIRED)
        counters["suspect_replicas"] = sum(
            1 for r in reps if r["state"] == SUSPECT)
        counters["degraded"] = any(
            r["state"] == DEGRADED for r in reps)
        counters["roles"] = dict(collections.Counter(
            r["role"] for r in reps if r["state"] != RETIRED))
        counters["replicas"] = reps
        kv = self.kv_migration_stats()
        if kv is not None:
            counters["kv_migration"] = kv
        scaler = self._autoscaler
        if scaler is not None:
            counters["autoscale"] = scaler.stats()
        # per-role autoscalers (disaggregation): one block per side,
        # so both roles' scale decisions are visible in one snapshot
        by_role = {}
        for role, view in role_views.items():
            vs = getattr(view, "_autoscaler", None)
            if vs is not None:
                by_role[role] = vs.stats()
        if by_role:
            counters["autoscale_by_role"] = by_role
        wd = self._watchdog
        if wd is not None:
            counters["watchdog"] = wd.stats()
        return counters

    def _agg_numeric(self, per_replica: List[Optional[Dict[str, Any]]]
                     ) -> Optional[Dict[str, Any]]:
        dicts = [d for d in per_replica if d]
        if not dicts:
            return None
        out: Dict[str, Any] = {}
        for d in dicts:
            for k, v in d.items():
                if isinstance(v, bool) or not isinstance(
                        v, (int, float)):
                    out.setdefault(k, v)
                else:
                    out[k] = out.get(k, 0) + v
        return out

    def prefix_stats(self) -> Optional[Dict[str, Any]]:
        out = self._agg_numeric(
            [r.engine.prefix_stats() for r in self._replicas])
        if out:
            total = out.get("hit_tokens", 0) + out.get(
                "miss_tokens", 0)
            out["hit_rate"] = round(
                out.get("hit_tokens", 0) / total, 4) if total else 0.0
        return out

    def spec_stats(self) -> Optional[Dict[str, Any]]:
        out = self._agg_numeric(
            [r.engine.spec_stats() for r in self._replicas])
        if out:
            proposed = out.get("proposed", 0)
            out["accept_rate"] = round(
                out.get("accepted", 0) / proposed, 4) \
                if proposed else 0.0
            disp = out.get("dispatches", 0)
            if "tokens_per_dispatch" in out:
                out["tokens_per_dispatch"] = round(
                    (out.get("accepted", 0) + disp) / disp, 4) \
                    if disp else 0.0
        return out

    def lifecycle_stats(self) -> Dict[str, Any]:
        per = [r.engine.lifecycle_stats() for r in self._replicas]
        out = self._agg_numeric(per) or {}
        # knobs are per-replica config, not summable: report rep 0's
        for knob in ("max_queued", "max_retries", "retry_backoff_s"):
            if per:
                out[knob] = per[0].get(knob)
        return out

    def _role_capacity_eta_s(self) -> float:
        """Max in-flight provisioning ETA over the per-role
        autoscalers — the pool-wide ``capacity_hint_fn`` when role
        views are attached (either side's provisioning capacity can
        end an all-shed)."""
        eta = 0.0
        for view in list(self._role_views.values()):
            scaler = getattr(view, "_autoscaler", None)
            if scaler is None:
                continue
            try:
                eta = max(eta, float(scaler.capacity_eta_s()))
            except Exception:
                _metrics()["capacity_hint_errors"].inc()
        return eta


class _RoleEventLog:
    """Event seam a RolePoolView hands its autoscaler: appends land
    in the POOL's ring with the view's role injected into the data,
    so both sides' scale decisions interleave in one log and stay
    attributable."""

    def __init__(self, log: obs.EventLog, role: str):
        self._log = log
        self._role = role

    def append(self, etype: str, rid: Any = None, sid: Any = None,
               data: Any = None, t: Optional[float] = None) -> None:
        d = dict(data) if isinstance(data, dict) else (
            {"data": data} if data is not None else {})
        d["role"] = self._role
        self._log.append(etype, rid=rid, sid=sid, data=d, t=t)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._log, name)


class RolePoolView:
    """One disaggregated side of an EnginePool, shaped like a pool.

    ``PoolAutoscaler`` attaches to whatever it is given — ctor
    side-effects (``pool._autoscaler``, ``pool.capacity_hint_fn``)
    included — so two per-role scalers pointed at the SAME pool would
    clobber each other. Each scaler instead gets a view: load_report
    and counts filter to the role, ``add_replica``/``scale_down``
    scale only this side, events are tagged with the role, and the
    view registers itself on the pool so ``pool_stats`` shows both
    sides' decisions (``autoscale_by_role``) and the pool's own
    capacity hint becomes the max over the attached scalers' ETAs."""

    def __init__(self, pool: EnginePool, role: str):
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"unknown replica role {role!r}; expected one of "
                f"{sorted(REPLICA_ROLES)}")
        self._pool = pool
        self.role = role
        # PoolAutoscaler ctor attachment points land HERE, per view
        self._autoscaler = None
        self.capacity_hint_fn: Optional[Callable[[], float]] = None
        self.events = _RoleEventLog(pool.events, role)
        pool._role_views[role] = self
        pool.capacity_hint_fn = pool._role_capacity_eta_s

    # pool surface the autoscaler senses -----------------------------

    @property
    def _stopped(self) -> bool:
        return self._pool._stopped

    @property
    def add_replica_for_ticket(self):
        # provider-harvest override, honored pool-wide if installed
        return getattr(self._pool, "add_replica_for_ticket", None)

    def load_report(self) -> Dict[str, Any]:
        return self._pool.load_report(role=self.role)

    def active_count(self) -> int:
        with self._pool._lock:
            return sum(1 for r in self._pool._replicas
                       if r.state != RETIRED and r.role == self.role)

    def healthy_count(self) -> int:
        with self._pool._lock:
            return sum(1 for r in self._pool._replicas
                       if r.state == HEALTHY and r.role == self.role)

    # pool surface the autoscaler actuates ---------------------------

    def add_replica(self) -> int:
        return self._pool.add_replica(role=self.role)

    def scale_down(self, n: int = 1,
                   timeout_s: float = 30.0) -> List[int]:
        return self._pool.scale_down(n, timeout_s, role=self.role)
