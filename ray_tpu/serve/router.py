"""Client-side routing: DeploymentHandle.

Capability parity with the reference's Router/ReplicaSet
(serve/_private/router.py:62,221: pick a replica under its in-flight cap,
power-of-two-choices among non-saturated) and the LongPollClient config
push (serve/_private/long_poll.py:63): on the distributed runtime the
controller publishes its replica table to the head's pub/sub hub and
handles SUBSCRIBE — zero polling RPCs in steady state, scale events
visible push-latency fast. The local (in-process) runtime has no hub;
handles fall back to TTL refresh there.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu

_REFRESH_S = 0.25


class DeploymentMethod:
    def __init__(self, handle: "DeploymentHandle", method: str,
                 stream: bool = False,
                 multiplexed_model_id: Optional[str] = None):
        self._handle = handle
        self._method = method
        self._stream = stream
        self._model_id = multiplexed_model_id

    _UNSET = object()

    def options(self, *, stream: Optional[bool] = None,
                multiplexed_model_id: Any = _UNSET
                ) -> "DeploymentMethod":
        """Unspecified options inherit from this method binding;
        multiplexed_model_id='' explicitly clears multiplexing."""
        return DeploymentMethod(
            self._handle, self._method,
            self._stream if stream is None else stream,
            self._model_id if multiplexed_model_id is self._UNSET
            else (multiplexed_model_id or None))

    def remote(self, *args, **kwargs):
        if self._model_id:
            from ray_tpu.serve.multiplex import MUX_KWARG
            kwargs = dict(kwargs)
            kwargs[MUX_KWARG] = self._model_id
        if self._stream:
            return self._handle._route_stream(self._method, args,
                                              kwargs,
                                              model_id=self._model_id)
        return self._handle._route(self._method, args, kwargs,
                                   model_id=self._model_id)


class StreamingResponse:
    """Iterator over a streaming serve call's chunks (reference:
    DeploymentResponseGenerator, serve handle streaming). Pulls chunk
    batches from the replica with long-polls; releases the handle's
    in-flight slot when the stream ends."""

    def __init__(self, handle: "DeploymentHandle", replica, rid,
                 req_id: str):
        self._handle = handle
        self._replica = replica
        self._rid = rid
        self._req_id = req_id
        self._buf: List[Any] = []
        self._pos = 0          # chunks consumed from the replica
        self._done = False
        self._error: Optional[BaseException] = None
        self._released = False

    def __iter__(self):
        return self

    def __next__(self):
        while not self._buf and not self._done:
            try:
                out = ray_tpu.get(self._replica.next_chunks.remote(
                    self._req_id, self._pos))
            except BaseException:
                # transport failure (replica death, stream reaped):
                # the in-flight slot must not stay held
                self._done = True
                self._release()
                raise
            self._buf.extend(out["chunks"])
            self._pos += len(out["chunks"])
            if out["done"]:
                self._done = True
                self._error = out["error"]
                self._release()
        if self._buf:
            return self._buf.pop(0)
        # buffer drained: surface a mid-stream error, else finish
        if self._error is not None:
            err, self._error = self._error, None
            raise err
        raise StopIteration

    def _release(self):
        if not self._released:
            self._released = True
            self._handle._done(self._rid)

    def __del__(self):
        self._release()


class DeploymentHandle:
    def __init__(self, name: str, controller):
        self._name = name
        self._controller = controller
        self._lock = threading.Lock()
        self._init_runtime_state()

    def _init_runtime_state(self):
        self._replicas: List = []
        self._replica_ids: tuple = ()
        self._max_ongoing = 8
        self._version = -1
        self._fetched_at = 0.0
        self._inflight: Dict[Any, int] = {}   # replica id -> count
        self._loads: Dict[Any, dict] = {}     # replica id -> load
                                              # snapshot (controller
                                              # _poll_loads table)
        self._poll_count = 0        # controller RPCs (regression tests)
        self._push_active = False
        self._subscriber = None
        self._maybe_subscribe()

    def __reduce__(self):
        # Handles travel inside replica init args (deployment graphs);
        # locks/caches don't pickle — reconstruct from the name.
        return (_rebuild_handle, (self._name,))

    # --- replica set maintenance ------------------------------------------

    def _maybe_subscribe(self):
        """Long-poll push of the replica table (distributed runtime)."""
        from ray_tpu._private.worker import global_worker
        head = getattr(global_worker().runtime, "head", None)
        if head is None:
            return
        try:
            from ray_tpu.runtime.pubsub import Subscriber
            from ray_tpu.runtime.rpc import RpcClient
            sub = Subscriber(RpcClient(f"{head.host}:{head.port}"))
            sub.subscribe_state(f"serve:replicas:{self._name}",
                                self._on_push)
            self._subscriber = sub
        except Exception:
            pass       # fall back to TTL polling

    def _on_push(self, version: int, blob):
        if not blob:
            return
        import cloudpickle
        info = cloudpickle.loads(blob)
        with self._lock:
            self._push_active = True
            self._apply_locked(info)
            self._fetched_at = time.time()

    def _apply_locked(self, info):
        rids = tuple(rid for rid, _ in info["replicas"])
        if info["version"] != self._version or \
                rids != self._replica_ids:
            # Compare replica IDENTITIES, not counts: a health-check
            # replacement swaps a replica without bumping the version
            # or changing the count, and a handle that kept routing to
            # the dead actor would error until... forever. In-flight
            # counts are KEYED by replica id, so survivors keep their
            # counts across the swap (zeroing would over-admit onto
            # saturated replicas) and completions of requests
            # dispatched before the swap still decrement the right
            # replica; only departed replicas' counts are dropped.
            self._replicas = [h for _, h in info["replicas"]]
            self._replica_ids = rids
            live = set(rids)
            self._inflight = {rid: c for rid, c in
                              self._inflight.items() if rid in live}
            self._version = info["version"]
            # Affinity is rid-keyed too: only models homed on a
            # departed replica lose their pin (survivors keep their
            # warm caches through the swap).
            mux = getattr(self, "_mux_affinity", None)
            if mux:
                for mid in [m for m, r in mux.items() if r not in live]:
                    del mux[mid]
        self._max_ongoing = info["max_ongoing"]
        # Load snapshots ride the polling path only (pushes stay
        # scale-event-driven), so a push payload without them must
        # not wipe the last-known table.
        if "loads" in info:
            self._loads = info["loads"] or {}

    def replica_loads(self) -> Dict[Any, dict]:
        """Last-known per-replica load snapshots (engine/pool
        ``load_report`` via the controller's table)."""
        with self._lock:
            return dict(self._loads)

    def _load_key(self, i: int):
        """Routing tie-break from the load table: queue pressure
        first, outstanding token work second. Missing snapshot ==
        zero — absence of evidence must not repel traffic."""
        rpt = self._loads.get(self._replica_ids[i])
        if not rpt:
            return (0, 0)
        return (rpt.get("queue_depth", 0),
                rpt.get("outstanding_tokens", 0))

    def _refresh(self, force: bool = False):
        with self._lock:
            if self._push_active and self._replicas and not force:
                return      # push keeps us fresh: no polling
            if not force and time.time() - self._fetched_at < _REFRESH_S \
                    and self._replicas:
                return
            self._poll_count += 1
            info = ray_tpu.get(
                self._controller.get_replicas.remote(self._name))
            self._apply_locked(info)
            self._fetched_at = time.time()

    def _pick(self, model_id: Optional[str] = None):
        """Power-of-two-choices among replicas under the in-flight
        cap. Multiplexed requests prefer the replica that last served
        their model id (cache affinity — reference: the multiplexed
        routing policy in serve's router): affinity wins while that
        replica has capacity; otherwise the request spills to the
        balanced choice and the affinity map learns the new home.

        Returns (replica_handle, replica_id) — the id is what the
        caller must pass to _done(); indices shift when the replica
        set changes, ids never do."""
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                return None
            cnt = lambda i: self._inflight.get(self._replica_ids[i], 0)
            candidates = [i for i in range(n)
                          if cnt(i) < self._max_ongoing]
            if not candidates:
                return None
            idx = None
            if model_id:
                mux = getattr(self, "_mux_affinity", None)
                if mux is None:
                    mux = self._mux_affinity = {}
                home_rid = mux.get(model_id)     # affinity by rid
                if home_rid in self._replica_ids:
                    home = self._replica_ids.index(home_rid)
                    if home in candidates:
                        idx = home
            if idx is None:
                if len(candidates) == 1:
                    idx = candidates[0]
                else:
                    a, b = random.sample(candidates, 2)
                    if cnt(a) != cnt(b):
                        idx = a if cnt(a) < cnt(b) else b
                    else:
                        # equal in-flight: break the tie on the
                        # controller's load table (engine queue
                        # depth / outstanding tokens), so a replica
                        # whose ENGINE is backed up stops looking
                        # identical to an idle one
                        idx = a if (self._load_key(a)
                                    <= self._load_key(b)) else b
                if model_id:
                    self._mux_affinity[model_id] = \
                        self._replica_ids[idx]
                    # Bound the affinity map (ids churn in LoRA-style
                    # fleets).
                    if len(self._mux_affinity) > 4096:
                        self._mux_affinity.pop(
                            next(iter(self._mux_affinity)))
            rid = self._replica_ids[idx]
            self._inflight[rid] = self._inflight.get(rid, 0) + 1
            return self._replicas[idx], rid

    def _done(self, rid):
        with self._lock:
            if self._inflight.get(rid, 0) > 0:
                self._inflight[rid] -= 1

    # --- calls -------------------------------------------------------------

    def _acquire_replica(self, model_id: Optional[str] = None):
        """Returns (replica_handle, replica_id) with an in-flight
        slot held; the caller owes a _done(replica_id)."""
        deadline = time.time() + 30
        while True:
            self._refresh()
            picked = self._pick(model_id)
            if picked is not None:
                return picked
            if time.time() > deadline:
                raise TimeoutError(
                    f"No replica of {self._name!r} accepted the request "
                    f"within 30s (all at max_ongoing_requests)")
            time.sleep(0.005)
            self._refresh(force=True)

    def _route(self, method: str, args, kwargs,
               model_id: Optional[str] = None):
        replica, rid = self._acquire_replica(model_id)
        ref = replica.handle_request.remote(method, args, kwargs)
        self._watch_completion(ref, rid)
        return ref

    def _route_stream(self, method: str, args, kwargs,
                      model_id: Optional[str] = None
                      ) -> "StreamingResponse":
        import uuid
        replica, rid = self._acquire_replica(model_id)
        req_id = uuid.uuid4().hex
        try:
            ray_tpu.get(replica.handle_request_streaming.remote(
                req_id, method, args, kwargs))
        except BaseException:
            self._done(rid)      # failed start must release the slot
            raise
        return StreamingResponse(self, replica, rid, req_id)

    def _watch_completion(self, ref, rid):
        def _wait():
            try:
                ref.future().result()
            except Exception:
                pass
            finally:
                self._done(rid)
        threading.Thread(target=_wait, daemon=True).start()

    def remote(self, *args, **kwargs):
        return self._route("__call__", args, kwargs)

    def options(self, *, stream: bool = False,
                multiplexed_model_id: Optional[str] = None
                ) -> DeploymentMethod:
        """handle.options(stream=True).remote(...) returns a
        StreamingResponse iterator of chunks;
        options(multiplexed_model_id=...) routes with model-cache
        affinity and sets serve.get_multiplexed_model_id() in the
        replica."""
        return DeploymentMethod(self, "__call__", stream,
                                multiplexed_model_id)

    def __getattr__(self, name: str) -> DeploymentMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentMethod(self, name)


    def close(self):
        """Stop the push subscriber thread and its RPC connection."""
        sub, self._subscriber = self._subscriber, None
        if sub is not None:
            try:
                sub.stop()
            except Exception:
                pass
        self._push_active = False


# One handle per deployment per process: handles own a long-poll
# subscriber thread + RPC connection, so constructing one per
# get_handle()/unpickle would leak threads and sockets without bound.
_handle_cache: Dict[str, DeploymentHandle] = {}
_handle_cache_runtime: Any = None
_handle_cache_lock = threading.Lock()


def get_or_create_handle(name: str) -> DeploymentHandle:
    global _handle_cache_runtime
    from ray_tpu._private.worker import global_worker
    from ray_tpu.serve.controller import get_or_create_controller
    rt = global_worker().runtime
    with _handle_cache_lock:
        if _handle_cache_runtime is not rt:
            _clear_handles_locked()
            _handle_cache_runtime = rt
        h = _handle_cache.get(name)
        if h is None:
            h = DeploymentHandle(name, get_or_create_controller())
            _handle_cache[name] = h
        return h


def _clear_handles_locked():
    for h in _handle_cache.values():
        h.close()
    _handle_cache.clear()


def clear_handle_cache():
    """Close all cached handles (serve shutdown / runtime teardown)."""
    global _handle_cache_runtime
    with _handle_cache_lock:
        _clear_handles_locked()
        _handle_cache_runtime = None


def _rebuild_handle(name: str) -> "DeploymentHandle":
    return get_or_create_handle(name)
