"""Continuous-batching LLM engine with a paged KV cache.

Iteration-level scheduling (the vLLM idea, built TPU-first): requests
join and leave the decode batch at token granularity instead of
decode-to-completion batches. Supersedes the coalescing batch queue
for LLM serving (ref: python/ray/serve/batching.py:46,215 — which can
only batch whole calls; a long completion there blocks every rider).

TPU/XLA design:
- ONE jitted decode step, compiled once, processes a fixed set of
  ``max_slots`` decode slots every iteration (static shapes). Inactive
  slots point at the null page (page 0) and their outputs are ignored
  host-side — no lax.cond, no divergence, no retrace.
- KV lives in a paged pool (models/kv_cache.py): the host-side
  BlockAllocator hands pages to sequences as they grow; completion or
  preemption returns them. Memory is bounded by the pool, not by
  max_slots x max_len.
- Decode runs in chunks of ``chunk`` tokens per dispatch: one host
  sync per chunk amortizes the ~70ms tunneled-device readback latency
  (see generate_stream in models/llama.py) while keeping join/leave
  granularity at ``chunk`` tokens.
- Preemption is recompute-based: when the pool runs dry the youngest
  slot is evicted, its pages freed, and the request requeued with
  prompt = original prompt + tokens generated so far, so clients see
  an uninterrupted stream.
- Pool pages are DONATED to each jitted call, so XLA updates them in
  place — decode does not copy the cache every step.

Works for every Llama-shaped family (Llama, Mixtral) since they share
LlamaAttention via block_forward.
"""
from __future__ import annotations

import collections
import dataclasses
import itertools
import queue
import threading
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.kv_cache import (BlockAllocator, PagedKVLayer,
                                     init_kv_pool)

_DONE = object()


class RequestError(Exception):
    pass


@dataclasses.dataclass
class _Request:
    rid: int
    prompt: List[int]            # original prompt (never mutated)
    max_new_tokens: int
    out_q: "queue.Queue[Any]" = dataclasses.field(
        default_factory=queue.Queue)
    generated: List[int] = dataclasses.field(default_factory=list)
    preemptions: int = 0
    error: Optional[BaseException] = None

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.generated)

    @property
    def recompute_prompt(self) -> List[int]:
        """What to prefill after a preemption: everything the client
        has already seen."""
        return self.prompt + self.generated


class RequestHandle:
    """Client-side view of a submitted request."""

    def __init__(self, req: _Request):
        self._req = req

    def stream(self):
        """Yield generated token ids as they are produced."""
        while True:
            item = self._req.out_q.get()
            if item is _DONE:
                if self._req.error is not None:
                    raise self._req.error
                return
            yield item

    def result(self) -> List[int]:
        """Block until completion; return all generated token ids."""
        for _ in self.stream():
            pass
        return list(self._req.generated)


@dataclasses.dataclass
class _Slot:
    req: _Request
    pages: List[int]             # physical page ids, logical order
    pos: int                     # next KV write position
    cur: int                     # last sampled token (next step input)
    admit_seq: int               # LIFO preemption order


class LLMEngine:
    """Continuous-batching decode engine for one model replica.

    Parameters
    ----------
    model, params: a Llama-family flax module + params.
    max_slots: decode batch width (static; compile-time).
    page_size: tokens per KV page.
    n_pages: physical pages in the pool (page 0 reserved as null).
    chunk: decode steps per device dispatch (host-sync amortization).
    """

    def __init__(self, model, params, *, max_slots: int = 8,
                 page_size: int = 16, n_pages: int = 256,
                 chunk: int = 4, temperature: float = 0.0,
                 eos_id: Optional[int] = None, seed: int = 0,
                 max_prefill_compiles: int = 16):
        self.model = model
        self.cfg = model.config
        self.params = params
        self.S = max_slots
        self.Pg = page_size
        self.K = chunk
        self.temperature = temperature
        self.eos_id = eos_id
        # Page-table width == the attention gather window (L =
        # max_pages * page_size per slot), so cap it at what the model
        # can legally address rather than the whole pool.
        self.max_pages = min(n_pages - 1,
                             -(-self.cfg.max_seq_len // page_size))
        self.alloc = BlockAllocator(n_pages)
        self.pages = init_kv_pool(self.cfg, n_pages, page_size)
        self.slots: List[Optional[_Slot]] = [None] * max_slots
        self._wait: "collections.deque[_Request]" = collections.deque()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._rid = itertools.count()
        self._admit_seq = itertools.count()
        self._rng = jax.random.PRNGKey(seed)
        self._pending = None      # in-flight chunk: (tokens_dev, riders)
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self.stats: Dict[str, int] = collections.Counter()
        self._prefill_cache: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._max_prefill_compiles = max_prefill_compiles
        # same-length waiting requests prefill together (one jitted
        # call, bucketed batch) up to this width
        self._max_prefill_batch = 4
        self._decode_fn = self._build_decode()

    # ---------------------------------------------------------- public

    def submit(self, prompt_ids: List[int],
               max_new_tokens: int = 64) -> RequestHandle:
        prompt_ids = [int(t) for t in prompt_ids]
        if not prompt_ids:
            raise RequestError("empty prompt")
        if max_new_tokens < 1:
            raise RequestError("max_new_tokens must be >= 1")
        total = len(prompt_ids) + max_new_tokens
        need = -(-total // self.Pg)
        if need > self.alloc.n_pages - 1:
            raise RequestError(
                f"request needs {need} pages but pool has only "
                f"{self.alloc.n_pages - 1} usable pages")
        if total > self.cfg.max_seq_len:
            raise RequestError(
                f"prompt+completion {total} exceeds model "
                f"max_seq_len {self.cfg.max_seq_len}")
        req = _Request(next(self._rid), prompt_ids, max_new_tokens)
        with self._work:
            if self._stopped:
                raise RequestError("engine stopped")
            self._wait.append(req)
            self.stats["submitted"] += 1
            self._work.notify()
        return RequestHandle(req)

    def start(self) -> "LLMEngine":
        """Run the scheduler loop in a daemon thread."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="llm-engine", daemon=True)
            self._thread.start()
        return self

    def shutdown(self):
        with self._work:
            self._stopped = True
            for req in self._wait:
                req.error = RequestError("engine stopped")
                req.out_q.put(_DONE)
            self._wait.clear()
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)

    def step(self) -> bool:
        """One scheduler iteration, PIPELINED with the device:

            process chunk k's tokens  ->  admit  ->  grow/preempt
                                     ->  dispatch chunk k+1

        Chunk k+1 is dispatched while chunk k's readback is consumed —
        the device never waits on the host's ~70ms sync (decode feeds
        its own next-token on-device; the host only needs tokens for
        emission/completion, which tolerates one chunk of lag). This
        is iteration-level scheduling with async output processing
        (the vLLM multi-step idea, shaped for jax async dispatch).
        Returns False when idle."""
        with self._lock:
            self._process_pending_locked()
            self._admit_locked()
            if not any(self.slots):
                return self._pending is not None
            self._grow_or_preempt_locked()
            self._dispatch_chunk_locked()
            return True

    # ------------------------------------------------------- scheduler

    def _loop(self):
        while True:
            with self._work:
                while (not self._stopped and not self._wait
                       and not any(self.slots)
                       and self._pending is None):
                    self._work.wait()
                if self._stopped and not any(self.slots):
                    return
            try:
                self.step()
            except BaseException as e:   # fail every in-flight request
                self._fail_all(e)
                return

    def _fail_all(self, e: BaseException):
        with self._lock:
            for i, slot in enumerate(self.slots):
                if slot is not None:
                    slot.req.error = e
                    slot.req.out_q.put(_DONE)
                    self.slots[i] = None
            for req in self._wait:
                req.error = e
                req.out_q.put(_DONE)
            self._wait.clear()
            self._stopped = True

    def _admit_locked(self):
        while self._wait:
            free = [i for i, s in enumerate(self.slots) if s is None]
            if not free:
                return
            # Batched prefill: take the FIFO PREFIX of the wait queue
            # sharing the head request's padded length (fixed-shape
            # serving traffic batches fully; mixed lengths degrade to
            # batch 1 — never reordering past a different-length
            # request keeps admission fair).
            head_pad = -(-max(1, len(self._wait[0].recompute_prompt))
                         // self.Pg) * self.Pg
            group = []
            for req in self._wait:
                if len(group) >= min(len(free), self._max_prefill_batch):
                    break
                prompt = req.recompute_prompt
                pad = -(-max(1, len(prompt)) // self.Pg) * self.Pg
                if pad != head_pad:
                    break
                n0 = max(1, -(-len(prompt) // self.Pg))
                page_ids = self.alloc.alloc(n0)
                if page_ids is None:
                    break      # pool dry: wait for completions
                group.append((req, prompt, page_ids))
            if not group:
                return
            for _ in group:
                self._wait.popleft()
            try:
                firsts = self._prefill_batch(
                    [(p, pids) for _, p, pids in group], head_pad)
            except BaseException as e:
                for req, _p, pids in group:
                    self.alloc.free(pids)
                    req.error = e
                    req.out_q.put(_DONE)
                continue
            for (req, prompt, page_ids), first, ix in zip(
                    group, firsts, free):
                slot = _Slot(req=req, pages=page_ids,
                             pos=len(prompt), cur=first,
                             admit_seq=next(self._admit_seq))
                self.slots[ix] = slot
                self.stats["admitted"] += 1
                self._emit(ix, [first])

    def _grow_or_preempt_locked(self):
        """Ensure every active slot's pages cover this chunk's writes;
        evict the youngest slots if the pool runs dry."""
        for i in sorted(
                (i for i, s in enumerate(self.slots) if s is not None),
                key=lambda i: self.slots[i].admit_seq):
            slot = self.slots[i]
            if slot is None:        # evicted by an elder slot's growth
                continue
            steps = min(self.K, slot.req.remaining)
            need = -(-(slot.pos + steps) // self.Pg)
            while len(slot.pages) < need:
                got = self.alloc.alloc(need - len(slot.pages))
                if got is not None:
                    slot.pages.extend(got)
                    break
                victim = max(
                    (j for j, s in enumerate(self.slots)
                     if s is not None and j != i),
                    key=lambda j: self.slots[j].admit_seq,
                    default=None)
                if victim is None:
                    # alone and still can't grow: submit() guarantees a
                    # lone request fits, so this is a logic error
                    raise RuntimeError("page pool exhausted by one slot")
                self._preempt_locked(victim)

    def _preempt_locked(self, ix: int):
        slot = self.slots[ix]
        self.slots[ix] = None
        self.alloc.free(slot.pages)
        slot.req.preemptions += 1
        self.stats["preemptions"] += 1
        self._wait.appendleft(slot.req)   # front: re-admit first

    def _dispatch_chunk_locked(self):
        """Launch one K-step decode chunk asynchronously. The carry
        (pages, per-slot cur token) lives on device; the host records
        which slots rode the chunk and reads the tokens back NEXT
        step, overlapped with the following chunk's compute."""
        pt = np.zeros((self.S, self.max_pages), np.int32)
        pos = np.zeros((self.S,), np.int32)
        cur = np.zeros((self.S,), np.int32)
        riders = []
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            pt[i, :len(slot.pages)] = slot.pages
            pos[i] = slot.pos
            cur[i] = slot.cur
            riders.append((i, slot))
        toks, self.pages, self._rng = self._decode_fn(
            self.params, self.pages, jnp.asarray(pt),
            jnp.asarray(pos), jnp.asarray(cur), self._rng)
        # pos advances NOW (host mirror of the device carry); cur and
        # emission land at processing time
        for _i, slot in riders:
            slot.pos += self.K
        self._pending = (toks, riders)
        self.stats["chunks"] += 1
        self.stats["decode_steps"] += self.K

    def _process_pending_locked(self):
        """Consume the previous chunk's tokens (the only device->host
        sync). Runs while the NEXT chunk computes."""
        if self._pending is None:
            return
        toks_dev, riders = self._pending
        self._pending = None
        toks = np.asarray(toks_dev)           # overlapped readback
        for i, slot in riders:
            if self.slots[i] is not slot:
                continue      # preempted after dispatch: recompute
            # host mirror of cur for the NEXT dispatch (the device
            # already carried it forward internally during the chunk)
            slot.cur = int(toks[-1, i])
            accept = toks[:min(self.K, slot.req.remaining), i].tolist()
            self._emit(i, accept)

    def _emit(self, ix: int, tokens: List[int]):
        """Deliver tokens to the request; close out the slot when the
        request hits eos or its budget."""
        slot = self.slots[ix]
        req = slot.req
        done = False
        for t in tokens:
            t = int(t)
            req.generated.append(t)
            req.out_q.put(t)
            if ((self.eos_id is not None and t == self.eos_id)
                    or req.remaining <= 0):
                done = True
                break
        if done:
            self.slots[ix] = None
            self.alloc.free(slot.pages)
            self.stats["completed"] += 1
            req.out_q.put(_DONE)

    # ----------------------------------------------------- jitted fns

    def _prefill_batch(self, items, T0pad: int) -> List[int]:
        """Prefill up to _max_prefill_batch same-padded-length prompts
        in ONE jitted call (bucketed batch: pad rows with dummies that
        scatter into the null page). items: [(prompt, page_ids), ...]"""
        n = len(items)
        # FIXED batch width: one executable per prompt length (dummy
        # rows scatter into the null page). Bucketed widths would
        # compile B=1/2/4 variants lazily — measured as multi-second
        # p99 stalls mid-load; a few dummy prefill rows are far
        # cheaper than a retrace.
        B = self._max_prefill_batch
        n_pages = T0pad // self.Pg
        fn = self._prefill_cache.get((T0pad, B))
        if fn is None:
            fn = self._build_prefill(T0pad, B)
            self._prefill_cache[(T0pad, B)] = fn
            while len(self._prefill_cache) > self._max_prefill_compiles:
                self._prefill_cache.popitem(last=False)
        self._prefill_cache.move_to_end((T0pad, B))
        ids = np.zeros((B, T0pad), np.int32)
        lens = np.ones((B,), np.int32)
        pids = np.zeros((B, n_pages), np.int32)   # dummies -> null page
        for r, (prompt, page_ids) in enumerate(items):
            ids[r, :len(prompt)] = prompt
            lens[r] = len(prompt)
            pids[r, :len(page_ids)] = page_ids
        firsts, self.pages, self._rng = fn(
            self.params, jnp.asarray(ids), jnp.asarray(lens),
            self.pages, jnp.asarray(pids), self._rng)
        self.stats["prefills"] += 1
        self.stats["prefilled_seqs"] += n
        return [int(t) for t in np.asarray(firsts)[:n]]

    def _build_prefill(self, T0pad: int, B: int):
        model, cfg, Pg, temp = (self.model, self.cfg, self.Pg,
                                self.temperature)
        n_prompt_pages = T0pad // Pg
        from ray_tpu.models.llama import _pick_token, init_kv_caches

        def prefill(params, ids, true_lens, pages, page_ids, rng):
            rng, sub = jax.random.split(rng)
            caches = init_kv_caches(cfg, B, T0pad)
            logits, caches = model.apply(params, ids,
                                         kv_caches=caches, cache_len=0)
            flat_ids = page_ids.reshape(-1)     # [B * n_prompt_pages]
            new_pages = []
            for (pk, pv), (ck, cv) in zip(pages, caches):
                kp = ck.reshape(B * n_prompt_pages, Pg,
                                cfg.n_kv_heads, cfg.head_dim)
                vp = cv.reshape(B * n_prompt_pages, Pg,
                                cfg.n_kv_heads, cfg.head_dim)
                new_pages.append((
                    pk.at[flat_ids].set(kp.astype(pk.dtype)),
                    pv.at[flat_ids].set(vp.astype(pv.dtype))))
            last = logits[jnp.arange(B), true_lens - 1]    # [B, V]
            firsts = _pick_token(last, sub, temp)
            return firsts, new_pages, rng

        return jax.jit(prefill, donate_argnums=(3,))

    def _build_decode(self):
        model, K, temp = self.model, self.K, self.temperature
        from ray_tpu.models.llama import _pick_token

        def decode(params, pages, page_table, pos, cur, rng):
            def body(carry, _):
                pages, pos, cur, key = carry
                key, sub = jax.random.split(key)
                kv = [PagedKVLayer(pk, pv, page_table)
                      for pk, pv in pages]
                logits, new_kv = model.apply(
                    params, cur[:, None], kv_caches=kv, cache_len=pos)
                nxt = _pick_token(logits[:, -1], sub, temp)
                new_pages = [(c.pages_k, c.pages_v) for c in new_kv]
                return (new_pages, pos + 1, nxt, key), nxt
            (pages, _, _, key), toks = jax.lax.scan(
                body, (pages, pos, cur, rng), None, length=K)
            # the advanced key returns as device state: the host never
            # runs jax.random.split between chunks (each split is a
            # device dispatch — pure overhead on the decode hot loop)
            return toks, pages, key        # toks: [K, S]

        return jax.jit(decode, donate_argnums=(1,))
