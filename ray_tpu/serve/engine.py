"""Continuous-batching LLM engine with a paged KV cache.

Iteration-level scheduling (the vLLM idea, built TPU-first): requests
join and leave the decode batch at token granularity instead of
decode-to-completion batches. Supersedes the coalescing batch queue
for LLM serving (ref: python/ray/serve/batching.py:46,215 — which can
only batch whole calls; a long completion there blocks every rider).

TPU/XLA design:
- ONE jitted decode step, compiled once, processes a fixed set of
  ``max_slots`` decode slots every iteration (static shapes). Inactive
  slots point at the null page (page 0) and their outputs are ignored
  host-side — no lax.cond, no divergence, no retrace.
- KV lives in a paged pool (models/kv_cache.py): the host-side
  BlockAllocator hands pages to sequences as they grow; completion or
  preemption returns them. Memory is bounded by the pool, not by
  max_slots x max_len.
- Decode is DEVICE-PACED: per-slot next-token and write position live
  on device and chain dispatch-to-dispatch; admission seeds slot rows
  with an on-stream scatter; token readbacks trail asynchronously and
  only ever block on a dispatch older than the newest one. With a
  full batch the scheduler runs ahead to the next completion event
  (dispatch-time arithmetic when no eos is configured), so the host
  syncs exactly when a scheduling decision is possible — host round
  trips (~84ms through a tunneled device) never gate the token rate.
  Join/leave granularity under load is ``chunk`` tokens.
- Prefill is CHUNKED and interleaved with decode: prompts advance by
  at most ``prefill_chunk`` tokens per scheduling round (a shared
  per-round token budget packed across up to ``_max_prefill_batch``
  mid-prefill slots), and every round dispatches the prefill chunk
  immediately followed by a short decode chunk, so in-flight decode
  never stalls for a whole prompt the way monolithic padded-batch
  prefill stalls it. Admission only needs pages for the FIRST chunk
  (chunk-budget admission), later chunks grow pages like decode
  does. A request's first token is sampled by the chunk that
  consumes the END of its prompt and is emitted to the stream right
  then — TTFT is one prompt-prefill, not prompt-prefill plus a
  decode-chunk drain. The round planner itself is pure and
  device-free (serve/scheduler.py) so CPU tests drive it
  deterministically.
- Preemption is recompute-based: when the pool runs dry the youngest
  slot is evicted, its pages freed, and the request requeued with
  prompt = original prompt + tokens generated so far, so clients see
  an uninterrupted stream.
- Pool pages are DONATED to each jitted call, so XLA updates them in
  place — decode does not copy the cache every step.

Works for every Llama-shaped family (Llama, Mixtral) since they share
LlamaAttention via block_forward.
"""
from __future__ import annotations

import collections
import dataclasses
import itertools
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.kv_cache import (BlockAllocator, PagedKVLayer,
                                     export_page_bytes, init_kv_pool,
                                     kv_layer_store, kv_layer_view,
                                     kv_pool_page_bytes,
                                     page_cols_from_bytes)
from ray_tpu.serve import kv_migration, obs, spec_decode
# Typed lifecycle errors live in a jax-free module (serve/errors.py)
# so the HTTP proxy and clients can import them without the device
# stack; RequestError is re-exported here for existing call sites.
from ray_tpu.serve.errors import (DeadlineExceeded, EngineDraining,
                                  EngineOverloaded, EngineShutdown,
                                  RequestCancelled, RequestError)
from ray_tpu.serve.faults import EngineFault
from ray_tpu.serve.prefix_cache import PrefixCache
from ray_tpu.serve.scheduler import (LANE_BATCH, LANE_ONLINE,
                                     REPLICA_ROLES, ROLE_UNIFIED,
                                     StepPlan, SlotView, plan_step,
                                     role_plan_caps)

_DONE = object()

SHED_TOTAL = "serve_engine_shed_total"
CANCELLED_TOTAL = "serve_engine_cancelled_total"
DEADLINE_TOTAL = "serve_engine_deadline_exceeded_total"
CONTAINED_TOTAL = "serve_engine_contained_faults_total"
RETRIES_TOTAL = "serve_engine_retries_total"
BATCH_TOKENS_TOTAL = "serve_batch_tokens_total"
BATCH_PREEMPTED_TOTAL = "serve_batch_preempted_total"
WEIGHT_SWAP_TOTAL = "serve_weight_swap_total"
WEIGHT_ROLLBACK_TOTAL = "serve_weight_rollback_total"

_METRICS: Optional[dict] = None


def _metrics() -> dict:
    """Lazy module-level lifecycle metric singletons, re-created if a
    test's ``clear_registry()`` dropped them (same pattern as
    serve/prefix_cache.py)."""
    global _METRICS
    from ray_tpu.util import metrics
    if (_METRICS is None
            or metrics.registry().get(SHED_TOTAL)
            is not _METRICS["shed"]):
        _METRICS = {
            "shed": metrics.Counter(
                SHED_TOTAL, "Requests rejected at submit because the "
                "admission queue was at max_queued"),
            "cancelled": metrics.Counter(
                CANCELLED_TOTAL,
                "Requests aborted by the client (cancel/disconnect)"),
            "deadline_exceeded": metrics.Counter(
                DEADLINE_TOTAL,
                "Requests expired by their per-request deadline"),
            "contained_faults": metrics.Counter(
                CONTAINED_TOTAL, "Dispatch/readback faults contained "
                "to one request instead of failing the engine"),
            "retries": metrics.Counter(
                RETRIES_TOTAL, "Innocent requests requeued after a "
                "contained fault (bounded retry policy)"),
            "batch_tokens": metrics.Counter(
                BATCH_TOKENS_TOTAL, "Tokens emitted to BATCH-lane "
                "requests (the capacity the batch tier absorbed)"),
            "batch_preempted": metrics.Counter(
                BATCH_PREEMPTED_TOTAL, "BATCH-lane slots preempted "
                "— yielded to online traffic or page pressure; the "
                "request requeues and recomputes/prefix-resumes"),
            "weight_swaps": metrics.Counter(
                WEIGHT_SWAP_TOTAL, "In-place hot weight swaps "
                "applied (monotonic generation-fence flips between "
                "scheduler rounds)"),
            "weight_rollbacks": metrics.Counter(
                WEIGHT_ROLLBACK_TOTAL, "Fleet rollout rollbacks: a "
                "canaried generation failed its health/parity gates "
                "and the controller re-installed the old payload "
                "under a fresh generation"),
        }
    return _METRICS


WEIGHT_GENERATION_GAUGE = "serve_weight_generation"

_WEIGHT_GEN_GAUGE = None


def _weight_generation_gauge():
    """Lazy singleton for the per-replica weight-generation gauge
    (clear_registry()-proof, same pattern as _metrics())."""
    global _WEIGHT_GEN_GAUGE
    from ray_tpu.util import metrics
    if (_WEIGHT_GEN_GAUGE is None
            or metrics.registry().get(WEIGHT_GENERATION_GAUGE)
            is not _WEIGHT_GEN_GAUGE):
        _WEIGHT_GEN_GAUGE = metrics.Gauge(
            WEIGHT_GENERATION_GAUGE,
            "Weight generation currently serving on each replica "
            "(the monotonic swap fence; rollback still advances it "
            "— weights_id names the payload)",
            tag_keys=("replica",))
    return _WEIGHT_GEN_GAUGE


KV_BYTES_TOTAL = "serve_kv_bytes_total"

_KV_GAUGE = None


def _kv_bytes_gauge():
    """Lazy singleton for the KV byte-budget gauge (same
    clear_registry()-proof pattern as _metrics()). Tagged by kv_dtype
    so an fp/int8 A/B in one process exposes both samples."""
    global _KV_GAUGE
    from ray_tpu.util import metrics
    if (_KV_GAUGE is None
            or metrics.registry().get(KV_BYTES_TOTAL) is not _KV_GAUGE):
        _KV_GAUGE = metrics.Gauge(
            KV_BYTES_TOTAL,
            "Paged KV pool byte budget (all layers, incl. scales)",
            tag_keys=("kv_dtype",))
    return _KV_GAUGE


def _dev_ready(buf) -> bool:
    """True when a device array's computation has finished (readback
    would not block). Conservative False when the runtime can't say."""
    try:
        return bool(buf.is_ready())
    except Exception:
        return False


def _first_leaf(buf):
    """Representative device array of a readback entry. Logprob
    capture packs (tokens, logprobs) pairs out of one jitted call, so
    either leaf's readiness stands for the pair's."""
    return buf[0] if isinstance(buf, tuple) else buf


@dataclasses.dataclass
class _Request:
    rid: int
    prompt: List[int]            # original prompt (never mutated)
    max_new_tokens: int
    out_q: "queue.Queue[Any]" = dataclasses.field(
        default_factory=queue.Queue)
    generated: List[int] = dataclasses.field(default_factory=list)
    preemptions: int = 0
    error: Optional[BaseException] = None
    closed: bool = False         # _DONE delivered; drop late tokens
    t_submit: float = 0.0        # monotonic clock at submit()
    t_first: Optional[float] = None   # first token EMITTED to stream
    deadline: Optional[float] = None  # absolute monotonic deadline
    attempts: int = 0            # requeues after contained faults
    t_earliest: float = 0.0      # retry backoff: no re-admission
                                 # before this monotonic instant
    trace_id: Optional[str] = None    # request-scope trace id (minted
                                 # at the HTTP proxy, survives pool
                                 # resubmits)
    t_last_emit: Optional[float] = None   # last stream emission (for
                                 # the inter-token phase histogram)
    pull: Optional[Dict[str, Any]] = None  # cross-replica KV pull
                                 # hint from the router: {"hashes":
                                 # [...], ...opaque fetcher fields}.
                                 # Consumed EXACTLY ONCE at first
                                 # admission — cleared before the
                                 # pull starts, so a preemption or
                                 # fault requeue can never re-pull.
    batch: bool = False          # BATCH lane (priority="batch",
                                 # serve/batch_tier.py): preemptible
                                 # offline work. Admits only behind
                                 # every waiting online request, is
                                 # the first preemption victim, and
                                 # counts in its own queue-depth lane
                                 # so the autoscaler never scales for
                                 # preemptible backlog.
    logprobs: Optional[List[float]] = None
                                 # per-token sampling logprobs, index-
                                 # aligned with ``generated`` (RL
                                 # rollout capture, ray_tpu/rl). None
                                 # unless the engine was built with
                                 # ``capture_logprobs=True``; appended
                                 # by _emit_to in the same truncation
                                 # loop as the tokens, so eos/budget
                                 # cuts and preemption recompute keep
                                 # the two lists aligned by
                                 # construction.

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.generated)

    @property
    def recompute_prompt(self) -> List[int]:
        """What to prefill after a preemption: everything the client
        has already seen."""
        return self.prompt + self.generated


class RequestHandle:
    """Client-side view of a submitted request."""

    def __init__(self, req: _Request,
                 engine: Optional["LLMEngine"] = None):
        self._req = req
        self._engine = engine
        self._drained = False

    def cancel(self) -> bool:
        """Abort the request at whatever phase it is in — queued,
        mid-prefill, decoding, or mid-speculation. Its slot frees,
        its pages return to the allocator (shared prefix pages only
        drop their reference), and any ``stream()``/``result()``
        consumer unblocks with ``RequestCancelled``. Returns False
        when the request had already finished (tokens delivered or
        failed) — cancellation after completion is a no-op."""
        if self._engine is None:
            return False
        return self._engine._cancel(self._req)

    @property
    def done(self) -> bool:
        return self._req.closed

    @property
    def error(self) -> Optional[BaseException]:
        return self._req.error

    @property
    def weights_tag(self) -> Optional[str]:
        """``generation:weights_id`` of the serving engine at read
        time (the X-Model-Generation header value) — which weight
        payload a mid-rollout client was actually served by."""
        eng = self._engine
        if eng is None:
            return None
        gen = getattr(eng, "weight_generation", None)
        if gen is None:
            return None
        return f"{gen}:{getattr(eng, 'weights_id', None)}"

    def stream(self):
        """Yield generated token ids as they are produced."""
        while True:
            item = self._req.out_q.get()
            if item is _DONE:
                if self._req.error is not None:
                    raise self._req.error
                return
            yield item

    def result(self) -> List[int]:
        """Block until completion; return all generated token ids.
        Idempotent: once the stream has been drained (here or via
        ``stream()`` running to completion elsewhere), repeat calls
        return the cached tokens — or re-raise the terminal error —
        instead of blocking on an already-consumed queue."""
        if not self._drained:
            self._drained = True
            for _ in self.stream():
                pass
        if self._req.error is not None:
            raise self._req.error
        return list(self._req.generated)

    @property
    def ttft_s(self) -> Optional[float]:
        """Submit-to-first-emission latency, stamped when the first
        token is PUT ON THE REQUEST STREAM (end of this request's
        prefill) — not when a decode chunk later drains. None until
        the first token is out."""
        if self._req.t_first is None:
            return None
        return self._req.t_first - self._req.t_submit

    @property
    def logprobs(self) -> Optional[List[float]]:
        """Per-token sampling logprobs, index-aligned with
        ``result()``: entry i is log p(token_i | prefix) under the
        weights that sampled it. None unless the engine was built
        with ``capture_logprobs=True``. Read after ``done`` (or
        ``result()``) for the complete, truncation-consistent list —
        mid-stream reads see a prefix."""
        lp = self._req.logprobs
        return None if lp is None else list(lp)


@dataclasses.dataclass
class _Slot:
    req: _Request
    pages: List[int]             # physical page ids, logical order
    pos: int                     # next KV write position (host mirror;
                                 # the device carries the live value)
    cur: Optional[int]           # None until the slot's seed scatter
                                 # is dispatched; afterwards a sentinel
                                 # — the next-token input lives ON
                                 # DEVICE (dev_cur), never read back
                                 # for dispatching
    admit_seq: int               # LIFO preemption order
    prompt: List[int] = dataclasses.field(default_factory=list)
                                 # recompute-prompt snapshot being
                                 # prefilled (chunk by chunk)
    prefilled: int = 0           # prompt tokens whose KV is in pages
    decoded: int = 0             # decode steps ridden (dispatch-time
                                 # arithmetic, ahead of emission)
    preempted: bool = False     # in-flight tokens must be discarded
    shared: int = 0              # leading pages owned by the prefix
                                 # cache (read-only: COW — scatters
                                 # may only target pages >= shared)
    spec: Optional[Any] = None   # per-slot n-gram proposer
                                 # (spec_decode.NGramIndex); dies with
                                 # the slot on preemption, rebuilt at
                                 # re-admission — no stale drafts
    spec_pending: List[int] = dataclasses.field(default_factory=list)
                                 # drafts proposed at plan time,
                                 # consumed by this round's verify
    pulling: bool = False        # PULLING phase: a background thread
                                 # is pulling this request's prefix
                                 # KV from a peer replica. The slot
                                 # holds NO pages and rides NO
                                 # dispatch; the planner skips it
                                 # (SlotView.pulling) and the pull's
                                 # completion requeues the request at
                                 # the queue front for normal
                                 # admission (local hit or plain
                                 # prefill fallback).

    @property
    def prefill_remaining(self) -> int:
        return len(self.prompt) - self.prefilled


class LLMEngine:
    """Continuous-batching decode engine for one model replica.

    Parameters
    ----------
    model, params: a Llama-family flax module + params.
    max_slots: decode batch width (static; compile-time).
    page_size: tokens per KV page.
    n_pages: physical pages in the pool (page 0 reserved as null).
    chunk: decode steps per device dispatch (host-sync amortization).
    prefill_chunk: prompt-token budget per scheduling round, shared
        across the mid-prefill slots scheduled that round. Prompts
        longer than this prefill over several rounds with decode
        chunks interleaved between them, so a long arrival cannot
        stall in-flight streams; smaller values tighten decode
        latency under prefill load, larger values finish prompts
        (and thus first tokens) in fewer rounds.
    prefix_cache: share KV pages of identical page-aligned prompt
        prefixes across requests (radix tree + refcounts + LRU
        eviction, serve/prefix_cache.py). Repeated system-prompt /
        few-shot prefixes then admit at near-zero prefill cost.
    spec_len: speculative decoding (serve/spec_decode.py) — up to
        this many prompt-lookup draft tokens per slot per round,
        verified by ONE batched multi-token forward pass through the
        paged ``T>=1`` branch; the longest argmax-matching draft
        prefix (plus one bonus token) is kept, rejections roll back
        by clamping the slot's KV offset. 0 (default) disables.
        Greedy-only: sampling (temperature > 0) would need
        distribution-preserving rejection sampling, so speculation
        is silently disabled then — the accepted stream must stay
        bit-identical to non-speculative decode. Spec rounds are
        host-synchronous (acceptance gates the next dispatch), so
        the engine drains readbacks every round like the eos path.
    spec_ngram: suffix n-gram order for the prompt-lookup proposer.
    spec_proposer: test seam — a zero-arg factory returning an
        object with the NGramIndex protocol (sync/propose), built
        once per admitted slot.
    max_queued: bounded admission — with more than this many
        requests already waiting, ``submit`` fails fast with
        ``EngineOverloaded`` (shed counter + 429 at the proxy)
        instead of queueing into silent TTFT collapse. None
        (default) keeps the queue unbounded. Counts ONLY the online
        lane: preemptible batch backlog lives under
        ``max_queued_batch``.
    max_queued_batch: the BATCH lane's own admission bound (None,
        default, = unbounded — the no-TTFT-SLO deep queue of the
        throughput profile; the batch driver bounds its own in-flight
        window instead, serve/batch_tier.py).
    max_retries: bounded retry policy for fault containment — an
        innocent request swept up in another request's dispatch
        fault is requeued (recompute, like preemption) at most this
        many times before it fails too.
    retry_backoff_s: base of the exponential re-admission backoff
        after a contained fault (``backoff * 2**(attempt-1)``).
    shed_retry_after_s: the Retry-After hint carried by
        ``EngineOverloaded`` (surfaced as the HTTP header).
    admit_timeout_s: bound on how long ``submit`` may wait for the
        scheduler lock before shedding typed ``EngineOverloaded``.
        None (default) blocks indefinitely; set it when a watchdog
        guards the engine so callers racing a WEDGED scheduler
        (serve/watchdog.py) shed-and-reroute instead of parking on
        a lock only teardown would release.
    fault_injector: test-only seam (serve/faults.py FaultInjector);
        None in production — every site is then a no-op.
    overlap: overlapped hot loop (default on). Each round plans and
        dispatches round N+1 from the PREVIOUS round's token frontier
        while round N still executes on device — the pre-plan drain
        only reads buffers the device has already finished, so the
        host never blocks before planning even in eos mode.
        Completion detection moves to readback time: a slot may
        over-decode past a late-revealed eos by at most one decode
        chunk (the planner caps stale riders, serve/scheduler.py),
        emission truncates at the eos exactly as before, and the
        overshot KV frontier is reclaimed by the same
        clamp-and-reseed machinery spec-decode rollback uses.
        ``overlap=False`` restores the lockstep loop (full blocking
        drain before planning in eos/spec mode — the PR-10 latency
        profile). Env ``RAY_TPU_OVERLAP=0``/``1`` force-overrides
        the knob for A/B runs without touching call sites.
    capture_logprobs: record the sampling logprob of every emitted
        token (RL rollout capture, ray_tpu/rl). The jitted decode and
        prefill steps compute ``log_softmax`` of the sampling logits
        and gather the chosen token's logprob into a float32 buffer
        that rides the existing trailing-readback path — no extra
        host syncs, no extra dispatches. Tokens and logprobs stay
        index-aligned through eos/budget truncation and preemption
        recompute because emission appends both in one loop. Read via
        ``RequestHandle.logprobs``. Speculative decoding is silently
        disabled under capture (the verify path emits tokens without
        per-token distributions — same auto-disable contract as
        temperature > 0). Off by default: serving pays nothing.
    kv_dtype: KV pool storage dtype. ``"fp"``/None stores cfg.dtype
        pages (exact). ``"int8"`` stores quantized pages with one
        fp32 absmax scale per (kv_head, physical page) — half the
        page bytes, so a fixed byte budget holds ~2x the pages/slots
        /prefix residency. Outputs are tolerance-equal to fp (greedy
        token agreement gated in tests; spec accept-rate unchanged
        within noise), NOT bit-equal: quantized bytes depend on
        write history (docs/serving.md). Env ``RAY_TPU_KV_DTYPE``
        overrides; junk values raise EnvKnobError.
    """

    def __init__(self, model, params, *, max_slots: int = 8,
                 page_size: int = 16, n_pages: int = 256,
                 chunk: int = 4, prefill_chunk: Optional[int] = None,
                 max_run_ahead: Optional[int] = None,
                 temperature: float = 0.0,
                 eos_id: Optional[int] = None, seed: int = 0,
                 max_prefill_compiles: int = 16,
                 prefix_cache: bool = False,
                 spec_len: int = 0, spec_ngram: int = 3,
                 spec_proposer=None,
                 max_queued: Optional[int] = None,
                 max_queued_batch: Optional[int] = None,
                 max_retries: int = 2,
                 retry_backoff_s: float = 0.02,
                 shed_retry_after_s: float = 1.0,
                 admit_timeout_s: Optional[float] = None,
                 sharding=None,
                 fault_injector=None,
                 events: bool = True,
                 flight_dir: Optional[str] = None,
                 overlap: Optional[bool] = None,
                 kv_dtype: Optional[str] = None,
                 prefix_digest_max: int = 512,
                 role: str = ROLE_UNIFIED,
                 capture_logprobs: bool = False):
        self.model = model
        self.cfg = model.config
        # Tensor-parallel placement (serve/sharding.py
        # EngineSharding): weights go down per the family's partition
        # rules, the KV pool head-shards over the ``tensor`` axis, and
        # every host->device operand commits replicated via _h2d.
        # Everything below the placement layer is sharding-oblivious —
        # same planner, same jitted step structure, same page tables.
        self._sharding = sharding
        if sharding is not None:
            params = sharding.shard_params(params)
        self.params = params
        # Weight-generation fence (live rollout, serve/weight_rollout):
        # strictly monotonic — every ``swap_weights`` must advance it,
        # including rollbacks (which install the OLD payload under a
        # NEW generation). ``weights_id`` names the payload itself so
        # convergence proofs can tell "rolled forward" from "rolled
        # back" when the generation alone cannot. ``replica_tag`` is
        # stamped by the pool (like ``role``) so the per-replica
        # generation gauge is attributable.
        self.weight_generation = 0
        self.weights_id = "g0"
        self.replica_tag = "0"
        self._pending_swap: Optional[Dict[str, Any]] = None
        self.S = max_slots
        self.Pg = page_size
        self.K = chunk
        self.PC = max(1, int(prefill_chunk or 256))
        self.temperature = temperature
        self.eos_id = eos_id
        # Run-ahead ceiling: one dispatch may decode up to this many
        # steps before a host sync (the token buffer is [KMAX, S]).
        # The throughput profile (scheduler.SCHEDULER_PROFILES) sets
        # it explicitly — batch decode tolerates longer syncs.
        self.KMAX = (max(chunk, 128) if max_run_ahead is None
                     else max(chunk, int(max_run_ahead)))
        # Page-table width == the attention gather window (L =
        # max_pages * page_size per slot), so cap it at what the model
        # can legally address rather than the whole pool.
        self.max_pages = min(n_pages - 1,
                             -(-self.cfg.max_seq_len // page_size))
        # KV storage dtype: "fp" (cfg.dtype pages, PR 1-14 behavior)
        # or "int8" (quantized pages + per-page scales, half the page
        # bytes -> double the pages at a fixed byte budget). The env
        # override RAY_TPU_KV_DTYPE wins over the constructor arg so
        # bench/chaos harnesses can flip whole fleets; junk values in
        # either raise typed errors (util/envknobs.py).
        from ray_tpu.util.envknobs import resolve_kv_dtype
        self.kv_dtype = resolve_kv_dtype(kv_dtype)
        # Disaggregation role (serve/scheduler.py REPLICA_ROLES):
        # selects the planner knob clamps via role_plan_caps and is
        # stamped into every load_report so routing, autoscaling, and
        # flight bundles all see the same topology. Mutable on
        # purpose — EnginePool stamps roles after construction so one
        # engine factory serves both pools.
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"unknown replica role {role!r}; expected one of "
                f"{sorted(REPLICA_ROLES)}")
        self.role = role
        self.page_bytes = kv_pool_page_bytes(self.cfg, page_size,
                                             self.kv_dtype)
        self.alloc = BlockAllocator(n_pages,
                                    page_bytes=self.page_bytes)
        self.pages = init_kv_pool(self.cfg, n_pages, page_size,
                                  self.kv_dtype)
        if sharding is not None:
            self.pages = sharding.place_kv_pool(self.pages)
        # capacity gauge: the whole-pool byte budget this engine holds
        # (per process — chaos/fleet runs sum across scrapes). Set
        # once; pools are static-shape for the engine's lifetime.
        _kv_bytes_gauge().set(float(n_pages * self.page_bytes),
                              tags={"kv_dtype": self.kv_dtype})
        # Radix-tree prefix KV cache (serve/prefix_cache.py): retired
        # prompts' full pages enter the tree instead of the free list;
        # admission matches the longest cached prefix and skips its
        # prefill. Refcounted + LRU-evicted, so it costs nothing under
        # memory pressure. Off by default: sharing only pays when
        # prompts actually share page-aligned prefixes.
        self.prefix_cache = (PrefixCache(self.alloc, page_size)
                             if prefix_cache else None)
        self._copy_page_fn = (self._build_copy_page()
                              if prefix_cache else None)
        # Fleet prefix-cache digest advertisement cap: load reports
        # ship at most this many path hashes, truncated prefix-closed
        # longest/hottest-first (PrefixCache.digest) so fleet routing
        # traffic stays bounded as the tree grows.
        self.prefix_digest_max = max(0, int(prefix_digest_max))
        # Cross-replica KV migration (serve/kv_migration.py). The
        # REQUESTER side: ``kv_fetcher`` is injected by the pool/agent
        # — a callable(pull_plan) -> payload dict or None — and a
        # request submitted with a ``pull`` hint admits in the PULLING
        # phase, overlapping the transfer with other slots' work. The
        # DONOR side is the kv_pin_prefix/kv_export_pages/
        # kv_release_pages trio a KVDonor drives. Stats mirror the
        # process counters per engine (bench artifacts, pool_stats).
        self.kv_fetcher: Optional[Any] = None
        self.kv_migration_stats = kv_migration.new_stats()
        self._write_page_fn = None   # built on first pulled landing
        # RL rollout logprob capture: must be fixed before the jitted
        # decode/prefill builders run (they close over it).
        self.capture_logprobs = bool(capture_logprobs)
        # Speculative decoding (serve/spec_decode.py): greedy-only —
        # verification accepts drafts against the argmax, so with
        # sampling it would skew the output distribution. Silently
        # off at temperature > 0 (docs/serving.md), and under logprob
        # capture (the verify emits accepted tokens without per-token
        # sampling distributions).
        if spec_len < 0:
            raise ValueError("spec_len must be >= 0")
        if spec_len and spec_ngram < 1:
            raise ValueError("spec_ngram must be >= 1")
        self.spec_len = (spec_len if temperature <= 0.0
                         and not self.capture_logprobs else 0)
        self.spec_ngram = spec_ngram
        self._proposer_factory = (
            spec_proposer if spec_proposer is not None
            else (lambda: spec_decode.NGramIndex(spec_ngram)))
        self._verify_fn = None       # built on first spec dispatch
        self.slots: List[Optional[_Slot]] = [None] * max_slots
        self._wait: "collections.deque[_Request]" = collections.deque()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._rid = itertools.count()
        self._admit_seq = itertools.count()
        self._rng = self._h2d(jax.random.PRNGKey(seed))
        # trailing readbacks: [(buf_dev, [(ix, slot, take), ...], steps)]
        self._fetchq: "collections.deque" = collections.deque()
        # in-flight prefills: [(firsts_dev, [(ix, slot, row), ...])]
        self._pending_prefill: List = []
        # Device-authoritative decode state: the next-token input and
        # write position per slot LIVE ON DEVICE and chain dispatch to
        # dispatch — no host readback sits on the decode critical
        # path. Admission seeds rows via a jitted scatter (no sync);
        # host readbacks trail for emission only.
        self._dev_cur = self._h2d(jnp.zeros((max_slots,), jnp.int32))
        self._dev_pos = self._h2d(jnp.zeros((max_slots,), jnp.int32))
        # Without an eos the schedule is fully deterministic: slots
        # retire by arithmetic at dispatch time and host syncs never
        # gate scheduling. With an eos, completions depend on sampled
        # tokens — the LOCKSTEP loop drains readbacks before planning
        # every round; the OVERLAPPED loop (default) plans from the
        # stale frontier instead and detects eos at readback time.
        self._deferred = eos_id is None
        _env = os.environ.get("RAY_TPU_OVERLAP", "")
        if _env in ("0", "1"):
            self.overlap = _env == "1"
        else:
            self.overlap = True if overlap is None else bool(overlap)
        self._stopped = False
        self._draining = False
        # Progress heartbeat (watchdog signal, serve/watchdog.py):
        # touched lock-free at the top of every scheduling round, at
        # every dispatch completion, and at every readback drain — so
        # a long-but-moving prefill keeps it fresh while a wedged
        # dispatch (hung XLA call, stuck transfer) lets it go stale.
        # Plain float assignment: GIL-atomic, no lock required.
        self._hb = time.monotonic()
        # Zombie fence: set by force_kill(). A wedged step thread
        # that later wakes finds this and may neither commit tokens
        # (its requests are closed) nor publish pages into the prefix
        # cache (retire-path inserts divert to plain frees).
        self._force_killed = False
        self._thread: Optional[threading.Thread] = None
        self.stats: Dict[str, int] = collections.Counter()
        # Request-lifecycle knobs: bounded admission + bounded retry
        if max_queued is not None and max_queued < 0:
            raise ValueError("max_queued must be >= 0 or None")
        self.max_queued = max_queued
        if max_queued_batch is not None and max_queued_batch < 0:
            raise ValueError("max_queued_batch must be >= 0 or None")
        self.max_queued_batch = max_queued_batch
        self.max_retries = max(0, int(max_retries))
        self.retry_backoff_s = max(0.0, float(retry_backoff_s))
        self.shed_retry_after_s = float(shed_retry_after_s)
        if admit_timeout_s is not None and admit_timeout_s <= 0:
            raise ValueError("admit_timeout_s must be > 0 or None")
        self.admit_timeout_s = admit_timeout_s
        self._injector = fault_injector
        self._round = 0              # scheduling-round counter (the
                                     # fault seam's deterministic clock)
        # Chunked prefill compiles one executable per pow2 chunk
        # bucket (floor page_size, cap prefill_chunk) — a handful of
        # variants total, vs the old one-per-prompt-length cache
        # whose misses were measured as multi-second p99 stalls.
        self._prefill_cache: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._max_prefill_compiles = max_prefill_compiles
        # mid-prefill slots share each round's token budget up to
        # this batch width (one jitted call, fixed row count)
        self._max_prefill_batch = 4
        # Typed lifecycle event log (serve/obs.py): lock-free bounded
        # ring recording every request phase and scheduler action.
        # ``events=False`` is the A/B arm proving the log costs
        # nothing measurable. ``sched_trace`` stays as a compat view
        # rendering the four legacy dispatch-order tuple kinds.
        self.events = obs.EventLog(8192, name="engine",
                                   enabled=events)
        self._obs_enabled = bool(events)
        self.sched_trace = obs.SchedTraceView(self.events)
        # Flight recorder sink: when set, EngineFault containment and
        # whole-engine failure dump a postmortem bundle here.
        self.flight_dir = flight_dir
        # submit->first-emission latencies (seconds), most recent
        self.ttfts_s: "collections.deque" = \
            collections.deque(maxlen=4096)
        # exponentially-weighted TTFT (None until the first token is
        # emitted): the autoscaler's SLO signal — a windowed mean
        # would hide a fresh latency regression behind old samples
        self._ttft_ewma: Optional[float] = None
        self._ttft_ewma_alpha = 0.2
        # exponentially-weighted inter-token gap (online lane only):
        # the decode pool's autoscaler signal, the latency twin of
        # the TTFT EWMA above
        self._itl_ewma: Optional[float] = None
        self._itl_ewma_alpha = 0.2
        self._decode_fn = self._build_decode()
        self._seed_fn = self._build_seed()

    def _h2d(self, x):
        """Host->device for dispatch operands (page tables, token
        chunks, positions, rng keys). Unsharded: plain jnp.asarray
        (byte-identical to the pre-TP engine). Sharded: commit
        REPLICATED onto the replica's mesh — an uncommitted
        single-device array would make every jitted call re-broadcast
        it from device 0 and spam donation warnings."""
        if self._sharding is None:
            return jnp.asarray(x)
        return self._sharding.replicate(jnp.asarray(x))

    def _constrain_kv(self, pages):
        """Pin a jitted step's output KV pool to the head-sharded
        layout (no-op unsharded). Keeps GSPMD from ever resharding
        the pool mid-graph — resharding would break the
        donate-and-alias discipline AND introduce KV collectives."""
        if self._sharding is None:
            return pages
        return self._sharding.constrain_kv(pages)

    # ---------------------------------------------------------- public

    def submit(self, prompt_ids: List[int],
               max_new_tokens: int = 64,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None,
               pull: Optional[Dict[str, Any]] = None,
               priority: str = LANE_ONLINE) -> RequestHandle:
        """Queue one request. ``deadline_s`` (relative, seconds) sets
        a hard completion deadline: the request fails with
        ``DeadlineExceeded`` at whatever phase it is in — queued,
        mid-prefill, decoding, mid-speculation — the first scheduling
        round after the deadline passes, and its resources free
        immediately. With ``max_queued`` configured, a full admission
        queue sheds the request with ``EngineOverloaded`` instead of
        accepting unbounded latency.

        ``priority`` selects the lane: ``"online"`` (default, the
        latency-critical path) or ``"batch"`` (preemptible offline
        work, serve/batch_tier.py). A batch request admits only when
        no online request is waiting, yields its slot the moment
        online traffic needs it (recompute/prefix-cache resume on
        re-admission, token-identical), and is bounded by
        ``max_queued_batch`` instead of ``max_queued`` — so a deep
        batch backlog can neither shed nor delay online admission.

        ``pull`` is a cross-replica KV pull hint from pool routing
        (serve/kv_migration.py): a dict carrying at least ``hashes``
        (the prompt's leading rolling path hashes a peer replica
        advertised as resident) plus whatever opaque fields the
        injected ``kv_fetcher`` needs to reach the donor. Admission
        then enters the PULLING phase instead of recomputing the
        prefix — see ``_admit_locked``. Ignored without a fetcher or
        prefix cache."""
        prompt_ids = [int(t) for t in prompt_ids]
        if not prompt_ids:
            raise RequestError("empty prompt")
        if max_new_tokens < 1:
            raise RequestError("max_new_tokens must be >= 1")
        if deadline_s is not None and deadline_s <= 0:
            raise RequestError("deadline_s must be > 0")
        if priority not in (LANE_ONLINE, LANE_BATCH):
            raise RequestError(
                f"unknown priority {priority!r}; expected "
                f"'{LANE_ONLINE}' or '{LANE_BATCH}'")
        total = len(prompt_ids) + max_new_tokens
        need = -(-total // self.Pg)
        if need > self.alloc.n_pages - 1:
            raise RequestError(
                f"request needs {need} pages but pool has only "
                f"{self.alloc.n_pages - 1} usable pages")
        if total > self.cfg.max_seq_len:
            raise RequestError(
                f"prompt+completion {total} exceeds model "
                f"max_seq_len {self.cfg.max_seq_len}")
        req = _Request(next(self._rid), prompt_ids, max_new_tokens,
                       t_submit=time.monotonic(), trace_id=trace_id,
                       pull=pull, batch=(priority == LANE_BATCH))
        if self.capture_logprobs:
            req.logprobs = []
        if deadline_s is not None:
            req.deadline = req.t_submit + deadline_s
        self.events.append("submit", rid=req.rid, t=req.t_submit,
                           data={"trace_id": trace_id,
                                 "prompt_len": len(prompt_ids),
                                 "max_new_tokens": max_new_tokens,
                                 "lane": priority})
        # Bounded admission-lock acquire: the scheduler holds this
        # lock across whole rounds, and a WEDGED scheduler (hung
        # dispatch — see serve/watchdog.py) holds it forever. With a
        # timeout configured, a stalled acquire sheds typed
        # EngineOverloaded instead of parking the caller on a lock
        # only teardown would release — the pool treats the shed as
        # "exclude this replica and route on".
        if self.admit_timeout_s is not None:
            acquired = self._work.acquire(
                timeout=self.admit_timeout_s)
        else:
            acquired = self._work.acquire()
        if not acquired:
            self.stats["admit_timeouts"] += 1
            self.events.append("shed", rid=req.rid,
                               data={"why": "admit_timeout"})
            raise EngineOverloaded(
                f"admission lock unavailable for "
                f"{self.admit_timeout_s}s (scheduler stalled); "
                f"request shed",
                retry_after_s=self.shed_retry_after_s)
        try:
            if self._stopped:
                raise EngineShutdown("engine stopped")
            if self._draining:
                raise EngineDraining(
                    "engine draining: finishing in-flight work, "
                    "admitting nothing new")
            # Per-lane bounded admission: the online bound counts
            # only online requests (a deep preemptible batch backlog
            # must never shed latency-critical traffic), and the
            # batch lane carries its own, typically much deeper (or
            # unbounded) budget — the throughput profile's
            # no-TTFT-SLO deep queue.
            bound = (self.max_queued_batch if req.batch
                     else self.max_queued)
            if bound is not None:
                lane_depth = sum(1 for r in self._wait
                                 if r.batch == req.batch)
                if lane_depth >= bound:
                    self.stats["shed"] += 1
                    _metrics()["shed"].inc()
                    self.events.append(
                        "shed", rid=req.rid,
                        data={"why": "queue_full",
                              "lane": priority})
                    raise EngineOverloaded(
                        f"admission queue full ({lane_depth} "
                        f"{priority} waiting >= "
                        f"max_queued{'_batch' if req.batch else ''}="
                        f"{bound}); request shed",
                        retry_after_s=self.shed_retry_after_s)
            self._wait.append(req)
            self.stats["submitted"] += 1
            self._work.notify()
        finally:
            self._work.release()
        return RequestHandle(req, self)

    def submit_rollout_batch(self, prompts: List[List[int]],
                             max_new_tokens: int = 64,
                             deadline_s: Optional[float] = None,
                             trace_id: Optional[str] = None
                             ) -> List[RequestHandle]:
        """Rollout-batch submit surface (ray_tpu/rl): queue one
        BATCH-lane request per prompt, in order, and return the
        handles. Batch-lane semantics are exactly the RL generator's
        needs — admits only behind online traffic, first preemption
        victim, excluded from the TTFT SLO signals — so a co-located
        online workload keeps its latency while rollouts soak the
        leftover capacity. ``trace_id`` (if given) stamps each
        request as ``{trace_id}:{i}``; per-token logprobs ride the
        handles when the engine was built with
        ``capture_logprobs=True``."""
        return [self.submit(list(p), max_new_tokens=max_new_tokens,
                            deadline_s=deadline_s,
                            trace_id=(f"{trace_id}:{i}"
                                      if trace_id else None),
                            priority=LANE_BATCH)
                for i, p in enumerate(prompts)]

    def start(self) -> "LLMEngine":
        """Run the scheduler loop in a daemon thread."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="llm-engine", daemon=True)
            self._thread.start()
        return self

    def drain(self) -> None:
        """Enter drain mode: admit nothing new, finish everything
        already queued or in flight. Direct ``submit`` calls fail
        typed ``EngineDraining`` (503 at the proxy); pool routing
        skips draining replicas entirely. Idempotent. Pair with
        ``wait_idle`` then ``shutdown`` for a graceful restart."""
        with self._work:
            self._draining = True
            self._work.notify_all()

    @property
    def draining(self) -> bool:
        return self._draining

    def reset_latency_stats(self) -> None:
        """Forget TTFT samples and the EWMA accumulated so far.
        For warmup scrubbing: a deployment compiles a replica with a
        throwaway request before it joins the fleet, and that
        compile-priced TTFT is not client experience — left in the
        EWMA it reads as a permanent SLO breach to the autoscaler."""
        with self._lock:
            self.ttfts_s.clear()
            self._ttft_ewma = None
            self._itl_ewma = None

    def is_idle(self) -> bool:
        """True when no request is queued, slotted, or trailing in a
        readback — the state a draining replica must reach before it
        can restart without failing anyone."""
        with self._lock:
            return (not self._wait and not any(self.slots)
                    and not self._fetchq
                    and not self._pending_prefill)

    def wait_idle(self, timeout_s: float = 30.0) -> bool:
        """Block until ``is_idle`` (or timeout). Returns the final
        idleness — False means in-flight work outlived the budget and
        the caller decides whether to axe it (``shutdown``)."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        while not self.is_idle():
            if time.monotonic() >= deadline:
                return self.is_idle()
            time.sleep(0.005)
        return True

    # ------------------------------------------- live weight rollout

    def swap_weights(self, params, *, generation: Optional[int] = None,
                     weights_id: Optional[str] = None,
                     mode: str = "preempt", wait: bool = True,
                     timeout_s: float = 120.0) -> int:
        """In-place hot weight swap under traffic.

        The new payload is staged onto the device OFF the engine lock
        (the double buffer: the old generation keeps serving while the
        transfer runs), then the flip happens between scheduler rounds
        — ``step()`` holds the engine lock for its entire round, so
        taking the lock here IS the inter-round boundary.

        ``mode="preempt"`` (default) flips immediately: trailing
        readbacks are drained so every victim's generated-so-far is
        complete, every active slot is preempted through the ordinary
        token-identical recompute path (the same arm replica death
        uses — prompt + generated re-prefill at the queue front), the
        prefix cache is cleared (no KV computed under the old weights
        may ever be matched against new-weight decode; per-slot spec
        proposers die with their slots), and the fence advances.

        ``mode="drain"`` pauses admission and applies the same flip
        once every slot, trailing readback, and pending prefill has
        settled — in-flight requests finish wholly on the old weights.

        The fence is strictly monotonic: a ``generation`` at or below
        the current one is refused with ``ValueError``. Roll BACK by
        installing the old payload under a NEW generation (a distinct
        ``weights_id`` names the payload). Returns the generation
        serving after the swap (with ``wait=False`` in drain mode:
        the generation that WILL serve once the drain settles)."""
        if mode not in ("preempt", "drain"):
            raise ValueError(f"unknown swap mode {mode!r}; expected "
                             f"'preempt' or 'drain'")
        if self._sharding is not None:
            staged = self._sharding.shard_params(params)
        else:
            staged = jax.tree_util.tree_map(jnp.asarray, params)
        jax.block_until_ready(jax.tree_util.tree_leaves(staged))
        with self._work:
            if self._stopped:
                raise EngineShutdown(
                    "cannot swap weights: engine stopped")
            gen = (self.weight_generation + 1 if generation is None
                   else int(generation))
            if gen <= self.weight_generation:
                raise ValueError(
                    f"weight-generation fence is monotonic: requested "
                    f"generation {gen} <= current "
                    f"{self.weight_generation} (install the old "
                    f"payload under a NEW generation to roll back)")
            wid = weights_id if weights_id is not None else f"g{gen}"
            if mode == "preempt":
                self._apply_swap_locked(staged, gen, wid, mode)
                self._work.notify_all()
                return gen
            if self._pending_swap is not None:
                raise RuntimeError(
                    "a drain-mode weight swap is already pending "
                    f"(generation "
                    f"{self._pending_swap['generation']})")
            pend = {"params": staged, "generation": gen,
                    "weights_id": wid, "applied": False,
                    "event": threading.Event()}
            self._pending_swap = pend
            self.events.append("weight_swap_pending",
                               data={"generation": gen,
                                     "weights_id": wid})
            self._work.notify_all()
        if not wait:
            return gen
        deadline = time.monotonic() + max(0.0, timeout_s)
        while not pend["event"].wait(timeout=0.05):
            if self._stopped:
                raise EngineShutdown(
                    "engine stopped with a weight swap pending")
            if time.monotonic() >= deadline:
                with self._work:
                    if self._pending_swap is pend:
                        self._pending_swap = None
                raise TimeoutError(
                    f"drain-mode weight swap to generation {gen} did "
                    f"not apply within {timeout_s}s")
        if not pend["applied"]:
            raise EngineShutdown(
                "engine stopped with a weight swap pending")
        return gen

    def _maybe_apply_pending_swap_locked(self) -> None:
        """Apply a pending drain-mode swap iff the engine has fully
        settled (no slots, no trailing readbacks, no in-flight
        prefills). Called between rounds by ``step()``."""
        pend = self._pending_swap
        if pend is None:
            return
        if (any(s is not None for s in self.slots) or self._fetchq
                or self._pending_prefill):
            return
        self._pending_swap = None
        self._apply_swap_locked(pend["params"], pend["generation"],
                                pend["weights_id"], "drain")
        pend["applied"] = True
        pend["event"].set()

    def _apply_swap_locked(self, staged, gen: int, wid: str,
                           mode: str) -> None:
        """The inter-round flip. Caller holds the engine lock and has
        validated the fence."""
        # settle trailing readbacks first so every preemption victim's
        # generated-so-far is complete before its recompute prompt
        # freezes (token-identity across the swap)
        self._drain_fetches_locked()
        preempted = 0
        for i in range(len(self.slots)):
            victim = self.slots[i]
            if victim is None:
                continue
            self._preempt_locked(i)
            if victim.preempted:
                preempted += 1
        # the fence's cache half: every slot was preempted (all shared
        # references released), so clear() evicts the whole radix tree
        # — no old-generation KV page survives to be matched against
        # new-generation decode
        evicted = 0
        if self.prefix_cache is not None:
            evicted = self.prefix_cache.clear()
        self.params = staged
        self.weight_generation = gen
        self.weights_id = wid
        self.stats["weight_swaps"] += 1
        _metrics()["weight_swaps"].inc()
        _weight_generation_gauge().set(
            float(gen),
            tags={"replica": str(getattr(self, "replica_tag", "0"))})
        self.events.append("weight_swap", data={
            "generation": gen, "weights_id": wid, "mode": mode,
            "preempted": preempted,
            "prefix_pages_evicted": evicted})
        self._hb = time.monotonic()

    def load_report(self) -> Dict[str, Any]:
        """Compact load snapshot for pool routing: free capacity,
        queue pressure, outstanding token work, and the prefix-cache
        digest (``PrefixCache.digest``) that longest-prefix affinity
        matches against.

        Best-effort consistency by design: tries the engine lock
        briefly, and otherwise reads lock-free — the scheduler
        mutates these fields under the GIL, so individual reads are
        safe and routing only needs freshness, not atomicity. A
        torn read costs one suboptimal route, never correctness."""
        def compute() -> Dict[str, Any]:
            outstanding = 0
            free_slots = 0
            for slot in list(self.slots):
                if slot is None:
                    free_slots += 1
                    continue
                req = slot.req
                outstanding += max(0, len(slot.prompt)
                                   - slot.prefilled)
                outstanding += max(0, req.max_new_tokens
                                   - len(req.generated))
            waiting = list(self._wait)
            for req in waiting:
                outstanding += len(req.prompt) + req.max_new_tokens
            q_batch = sum(1 for r in waiting if r.batch)
            return {
                "free_slots": free_slots,
                "total_slots": len(self.slots),
                "free_pages": self.alloc.n_free,
                # dtype-aware bytes view: the halving int8 buys shows
                # up wherever load_report lands (autoscaler signals,
                # pool_stats, flight bundles)
                "kv_dtype": self.kv_dtype,
                "kv_page_bytes": self.page_bytes,
                "kv_bytes_in_use": self.alloc.bytes_in_use(),
                "kv_bytes_total": self.alloc.bytes_total(),
                # Per-lane queue depth. ``queue_depth`` is the ONLINE
                # lane only — the number routing saturation
                # (Candidate.saturated vs max_queued) and the
                # autoscaler compare against their online-lane
                # bounds. Preemptible batch backlog is deliberately
                # its own number: scaling the fleet up for work that
                # yields instantly would defeat the tier.
                "queue_depth": len(waiting) - q_batch,
                "queue_depth_online": len(waiting) - q_batch,
                "queue_depth_batch": q_batch,
                "outstanding_tokens": outstanding,
                "max_queued": self.max_queued,
                "max_queued_batch": self.max_queued_batch,
                "shed_retry_after_s": self.shed_retry_after_s,
                "shed_total": self.stats.get("shed", 0),
                "ttft_ewma_s": self._ttft_ewma,
                "itl_ewma_s": self._itl_ewma,
                "role": self.role,
                "weight_generation": self.weight_generation,
                "weights_id": self.weights_id,
                "draining": self._draining,
                "stopped": self._stopped,
                "heartbeat_age_s": time.monotonic() - self._hb,
                # readback accounting: dispatches whose tokens are
                # still in flight. The overlapped loop holds this at
                # <= 2 (double-buffered) in steady state; a growing
                # depth means the trailing drain is starved.
                "fetchq_depth": len(self._fetchq),
                "pending_prefills": len(self._pending_prefill),
                "overlap": self.overlap,
                "has_work": bool(waiting or any(self.slots)
                                 or self._fetchq
                                 or self._pending_prefill),
                "tp": (self._sharding.tp
                       if self._sharding is not None else 1),
                "prefix_digest": (self.prefix_cache.digest(
                    self.prefix_digest_max)
                    if self.prefix_cache is not None
                    else frozenset()),
            }
        if self._lock.acquire(timeout=0.02):
            try:
                return compute()
            finally:
                self._lock.release()
        for _ in range(3):
            try:
                return compute()
            except RuntimeError:     # dict/deque mutated mid-iteration
                continue
        return {"free_slots": 0, "total_slots": len(self.slots),
                "free_pages": self.alloc.n_free,
                "kv_dtype": self.kv_dtype,
                "kv_page_bytes": self.page_bytes,
                "kv_bytes_in_use": self.alloc.bytes_in_use(),
                "kv_bytes_total": self.alloc.bytes_total(),
                "queue_depth": len(self._wait),
                "queue_depth_online": len(self._wait),
                "queue_depth_batch": 0,
                "outstanding_tokens": 0,
                "max_queued": self.max_queued,
                "max_queued_batch": self.max_queued_batch,
                "shed_retry_after_s": self.shed_retry_after_s,
                "shed_total": self.stats.get("shed", 0),
                "ttft_ewma_s": self._ttft_ewma,
                "itl_ewma_s": self._itl_ewma,
                "role": self.role,
                "weight_generation": self.weight_generation,
                "weights_id": self.weights_id,
                "draining": self._draining,
                "stopped": self._stopped,
                "heartbeat_age_s": time.monotonic() - self._hb,
                "fetchq_depth": len(self._fetchq),
                "pending_prefills": len(self._pending_prefill),
                "overlap": self.overlap,
                "has_work": bool(self._wait or any(self.slots)
                                 or self._fetchq
                                 or self._pending_prefill),
                "tp": (self._sharding.tp
                       if self._sharding is not None else 1),
                "prefix_digest": frozenset()}

    def force_kill(self, err: Optional[BaseException] = None) -> None:
        """Out-of-band kill for a WEDGED engine (watchdog escalation,
        serve/watchdog.py). A wedged scheduler thread is parked INSIDE
        ``step()`` HOLDING ``self._lock`` — every fault site fires
        under it — so ``shutdown()``'s lock-then-join would deadlock.
        This path takes NO lock: it sets the zombie fence + stop flag
        (GIL-atomic assignments) and fails every consumer so blocked
        ``stream()`` callers unblock immediately and the pool can
        resubmit. Resource cleanup (slot pages) happens later, when
        the wedge releases and the zombie thread unwinds — call
        ``shutdown()`` again after that for the final teardown.

        Zombie fence: after this, a step thread that later wakes
        cannot commit tokens (requests are closed; ``_emit_to``
        drops), cannot dispatch (the post-fire ``_stopped`` checks
        abandon the round), and cannot publish pages into the prefix
        cache (retire-path inserts divert to plain frees)."""
        err = err or EngineShutdown(
            "engine force-killed: wedged (no scheduler progress)")
        self.events.append("force_kill", data={"error": repr(err)})
        self._force_killed = True
        self._stopped = True

        def fail(req):
            if req.closed:
                return
            req.closed = True
            req.error = err
            req.out_q.put(_DONE)

        for slot in list(self.slots):
            if slot is not None:
                fail(slot.req)
        for item in list(self._fetchq):
            for _i, slot, _t in item[1]:
                fail(slot.req)
        for item in list(self._pending_prefill):
            for _ix, slot, _row in item[1]:
                fail(slot.req)
        for req in list(self._wait):
            fail(req)
        pend, self._pending_swap = self._pending_swap, None
        if pend is not None:
            pend["event"].set()   # waiter sees applied=False + raises
        self.stats["force_killed"] += 1

    def shutdown(self):
        """Stop the engine and FAIL everything still queued or in
        flight with a typed ``EngineShutdown`` — no ``stream()``/
        ``result()`` consumer may be left blocked. Tokens already
        computed (trailing readbacks of retired slots) are delivered
        first, so a request that effectively finished still resolves
        cleanly. Idempotent.

        After a ``force_kill`` the scheduler thread may still be
        wedged inside ``step()`` holding the engine lock, so this
        path must not block on it: the join is short and a
        still-alive thread defers the final resource cleanup to a
        later ``shutdown()`` call (after the wedge releases —
        ``FaultInjector.release_all()`` in tests)."""
        err = EngineShutdown("engine stopped")
        if self._force_killed:
            # consumers already failed lock-free; taking the lock
            # here would deadlock against the wedged step thread
            if self._thread is not None:
                self._thread.join(timeout=1.0)
                if self._thread.is_alive():
                    return      # still wedged: cleanup deferred
        else:
            with self._work:
                self._stopped = True
                self._work.notify_all()
            if self._thread is not None:
                self._thread.join(timeout=30)
        with self._work:
            # deliver what the device already produced before the axe
            try:
                self._drain_fetches_locked()
            except Exception:
                pass     # device gone: typed failure below still lands
            for i, slot in enumerate(self.slots):
                if slot is not None:
                    self._teardown_slot_locked(i, err)
            for _buf, riders, _steps in self._fetchq:
                for _i, slot, _t in riders:
                    self._fail_req_locked(slot.req, err)
            for _f, placements in self._pending_prefill:
                for _ix, slot, _row in placements:
                    self._fail_req_locked(slot.req, err)
            self._fetchq.clear()
            self._pending_prefill.clear()
            while self._wait:
                self._fail_req_locked(self._wait.popleft(), err)
            pend, self._pending_swap = self._pending_swap, None
            if pend is not None:
                pend["event"].set()   # waiter raises EngineShutdown

    def _cancel(self, req: _Request,
                error: Optional[BaseException] = None) -> bool:
        """Abort ``req`` at any phase (RequestHandle.cancel). Queued:
        removed and failed on the spot. Slotted (mid-prefill,
        decoding, mid-speculation): torn down synchronously — the
        lock serializes against the scheduler, and freeing pages
        under an in-flight dispatch is safe because device execution
        is stream-ordered (the same argument _retire_planned_locked
        rests on); trailing readbacks skip the closed request.
        Already-retired requests with tokens still in flight just
        close. Returns False iff the request had already finished."""
        err = error or RequestCancelled(
            f"request {req.rid} cancelled by client")
        with self._work:
            if req.closed:
                return False
            try:
                self._wait.remove(req)
                self._fail_req_locked(req, err, "cancelled")
                return True
            except ValueError:
                pass
            for i, slot in enumerate(self.slots):
                if slot is not None and slot.req is req:
                    self._teardown_slot_locked(i, err, "cancelled")
                    self._work.notify()
                    return True
            self._fail_req_locked(req, err, "cancelled")
            return True

    def _fail_req_locked(self, req: _Request, err: BaseException,
                         count: Optional[str] = None) -> None:
        """Resolve a request's consumers with a typed error, exactly
        once. ``count`` names the stats/metrics counter to bump."""
        if req.closed:
            return
        req.closed = True
        req.error = err
        req.out_q.put(_DONE)
        self.events.append(count or "failed", rid=req.rid,
                           data={"error": repr(err)})
        if count:
            self.stats[count] += 1
            m = _metrics().get(count)
            if m is not None:
                m.inc()

    def _teardown_slot_locked(self, ix: int, err: BaseException,
                              count: Optional[str] = None) -> None:
        """Fail a slotted request and free every resource it holds:
        the slot, its private pages (back to the allocator), and its
        shared prefix-page references (the tree keeps the KV).
        ``preempted`` is set so in-flight readback rows for this slot
        are discarded rather than emitted."""
        slot = self.slots[ix]
        self.slots[ix] = None
        slot.preempted = True
        self._free_slot_pages_locked(slot, retire=False)
        self._fail_req_locked(slot.req, err, count)

    def _reap_deadlines_locked(self) -> None:
        """Expire requests whose deadline passed — queued or slotted
        alike — with ``DeadlineExceeded``. Runs at the top of every
        scheduling round, so enforcement granularity is one round."""
        now = time.monotonic()
        for req in [r for r in self._wait if r.deadline is not None
                    and now >= r.deadline]:
            self._wait.remove(req)
            self._fail_req_locked(req, DeadlineExceeded(
                f"request {req.rid} missed its deadline while "
                f"queued"), "deadline_exceeded")
        for i, slot in enumerate(self.slots):
            if slot is None or slot.req.closed:
                continue
            if (slot.req.deadline is not None
                    and now >= slot.req.deadline):
                self._teardown_slot_locked(i, DeadlineExceeded(
                    f"request {slot.req.rid} missed its deadline "
                    f"after {len(slot.req.generated)} tokens"),
                    "deadline_exceeded")

    def _fire(self, site: str, sid: Optional[int] = None,
              rid: Optional[int] = None) -> None:
        """Fault-injection site (no-op without an injector)."""
        if self._injector is not None:
            self._injector.fire(site, self._round, sid, rid)

    def _alloc(self, n: int) -> Optional[List[int]]:
        """BlockAllocator.alloc behind the fault seam: an injected
        exhaustion makes the pool look dry for this one call,
        steering the caller into its real evict/preempt/wait
        recovery path."""
        if (self._injector is not None
                and self._injector.exhausted(self._round)):
            return None
        return self.alloc.alloc(n)

    def lifecycle_stats(self) -> Dict[str, Any]:
        """Request-lifecycle knobs + counters (bench artifacts and
        the replica stats hook read this)."""
        with self._lock:
            s = self.stats
            return {
                "max_queued": self.max_queued,
                "max_retries": self.max_retries,
                "retry_backoff_s": self.retry_backoff_s,
                "shed": s["shed"],
                "cancelled": s["cancelled"],
                "deadline_exceeded": s["deadline_exceeded"],
                "contained_faults": s["contained_faults"],
                "retries": s["retries"],
                "retry_exhausted": s["retry_exhausted"],
                "fault_failed": s["fault_failed"],
            }

    def step(self) -> bool:
        """One scheduler iteration, DEVICE-PACED:

            admit -> plan round -> dispatch prefill chunk
                  -> grow/preempt -> dispatch decode chunk k+1
                  -> fetch chunk k's tokens (trailing)

        The round packs a prefill chunk AND a decode chunk: both are
        dispatched asynchronously back to back, so the device
        pipeline interleaves ``P D P D ...`` and in-flight decode is
        delayed by at most one bounded prefill chunk per round —
        never by a whole prompt. Decode dispatch k+1 has NO data
        dependency on k's readback: the next-token input and write
        positions chain on device (dev_cur/dev_pos), seeding rides a
        jitted scatter, and — with no eos configured — completions
        are dispatch-time arithmetic. The readback of chunk k then
        overlaps chunk k+1's compute, so neither the device round
        trip nor a slow host thread gates the token rate.

        With an eos the loop is DOUBLE-BUFFERED (``overlap=True``,
        the default): the pre-plan drain is a non-blocking sweep, so
        round N+1 is planned from round N's (stale) frontier and its
        dispatches are committed while round N still executes; the
        trailing drain at the bottom then blocks on the OLDER of the
        two in-flight dispatches only (keep=1), pinning the pipeline
        depth at two and revealing each round's tokens at most one
        round late. A late-revealed eos costs at most one discarded
        decode chunk per slot — the planner caps stale riders
        (serve/scheduler.py) and emission truncates exactly where
        lockstep would. ``overlap=False`` restores the lockstep
        profile: sampled tokens decide completion, so the iteration
        drains readbacks fully before planning (the classic chunked
        loop). Returns False when idle.

        Failure containment: an ``EngineFault`` out of a dispatch
        section (fault-injection sites, or the now-attributable
        pool-exhausted-by-one-slot path) is handled HERE — the
        culprit request fails, the other participants of that
        dispatch requeue-or-fail under the bounded retry policy —
        and the engine keeps serving. Only non-attributable errors
        still escape to ``_fail_all`` via ``_loop``."""
        with self._lock:
            self._round += 1
            self._hb = time.monotonic()   # progress heartbeat: a new
                                          # round means the previous
                                          # one completed
            _pm = obs.phase_metrics() if self._obs_enabled else None
            _t0 = self._hb
            self._fire("step")     # global-fault site: escapes to
                                   # _fail_all, like real device loss
            if self._stopped:
                # force-killed while wedged at the step site: the
                # zombie fence forbids any further work this round
                return False
            self._reap_deadlines_locked()
            _tg = time.monotonic()
            if self.overlap:
                # Overlapped hot loop: plan round N+1 from the STALE
                # token frontier while round N still runs on device.
                # This sweep only reads buffers the device already
                # finished — it NEVER blocks, in eos mode either.
                # Completion detection moves to the trailing drain:
                # emission truncates at a late-revealed eos, the
                # planner caps stale riders at one decode chunk
                # (serve/scheduler.py SlotView.stale), and the
                # overshot KV frontier is reclaimed by the same
                # clamp-and-reseed machinery spec rollback uses. Spec
                # mode still syncs, but at its own dispatch
                # (_dispatch_spec_locked) — acceptance gates the NEXT
                # verify, not this round's prefill/decode lanes.
                self._drain_fetches_locked(ready_only=True)
            elif not self._deferred or self.spec_len:
                # Lockstep eos mode: emissions gate planning. Spec
                # mode: the proposer's context and the verify's input
                # token are HOST state (req.generated), so every
                # round syncs to the device before planning —
                # speculation trades the deferred pipeline's async
                # pacing for multi-token dispatches.
                self._drain_fetches_locked()
            else:
                # Opportunistic: read back anything already finished
                # BEFORE admitting — free on a fast local device, and
                # it gets completions to clients (whose resubmissions
                # can then land during the upcoming dispatch) a full
                # dispatch earlier. Never blocks.
                self._drain_fetches_locked(ready_only=True)
            _gap = time.monotonic() - _tg
            if self._pending_swap is not None:
                # drain-mode weight swap: admission is paused; flip
                # here — between rounds — once everything settled
                self._maybe_apply_pending_swap_locked()
            self._admit_locked()
            if not any(self.slots):
                if self._fetchq or self._pending_prefill:
                    self._drain_fetches_locked(limit=1)
                    return True
                # non-empty queue with nothing admitted = retry
                # backoff or a transiently dry pool: still working
                return bool(self._wait)
            if all(s is None or s.pulling for s in self.slots):
                # only PULLING slots live: nothing is dispatchable
                # until a transfer lands or aborts. Park on the
                # condition (the pull thread notifies on finish)
                # instead of spinning rounds; readbacks of already-
                # retired slots still drain.
                if self._fetchq or self._pending_prefill:
                    self._drain_fetches_locked(limit=1)
                else:
                    self._work.wait(timeout=0.01)
                return True
            _tp = time.monotonic()
            plan = self._plan_steps_locked()
            _tpe = time.monotonic()
            _gap += _tpe - _tp
            if _pm is not None:
                _pm["plan"].observe(_tpe - _tp)
            _td = time.monotonic() if _pm is not None else 0.0
            try:
                if plan.prefill:
                    self._dispatch_prefill_locked(plan.prefill)
            except EngineFault as e:
                e.sids = sorted({g.sid for g in plan.prefill}
                                | set(e.sids))
                self._contain_fault_locked(e)
                return True
            try:
                if plan.spec:
                    self._dispatch_spec_locked(plan.spec)
                elif plan.decode_steps:
                    riders = [i for i, s in enumerate(self.slots)
                              if s is not None and s.cur is not None]
                    self._grow_or_preempt_locked(plan.decode_steps)
                    self._dispatch_chunk_locked(plan.decode_steps)
                    if self._deferred:
                        self._retire_planned_locked()
            except EngineFault as e:
                part = ({g.sid for g in plan.spec} if plan.spec
                        else set(riders))
                e.sids = sorted(part | set(e.sids))
                self._contain_fault_locked(e)
                return True
            if _pm is not None:
                _pm["dispatch"].observe(time.monotonic() - _td)
            # trailing readback: block only on a dispatch OLDER than
            # the one just queued (keep=1), so the fetch round trip
            # overlaps the newest dispatch's compute — never its own
            self._drain_fetches_locked(limit=1, keep=1)
            _now = time.monotonic()
            # Per-round pipeline accounting: host_gap is the time the
            # host spent GATING this round's dispatches (pre-plan
            # drain + plan) — the fraction of round wall during which
            # the device could not be fed. The lockstep eos loop pays
            # a full device sync here every round; the overlapped
            # loop pays only a ready-buffer sweep. trace_report
            # derives overlap efficiency from these events; the
            # serve_phase_host_gap_s histogram is the aggregate
            # cross-check.
            self.events.append("round", data={
                "host_gap_s": round(_gap, 6),
                "wall_s": round(_now - _t0, 6),
                "overlap": self.overlap})
            if _pm is not None:
                _pm["round_wall"].observe(_now - _t0)
                _pm["host_gap"].observe(_gap)
            return True

    def _contain_fault_locked(self, e: EngineFault) -> None:
        """Per-slot failure containment: fail ONLY the culprit (the
        request the fault is attributable to) with the underlying
        error; every other slot that was participating in the
        poisoned dispatch is requeued tail-of-queue (recompute, like
        preemption) under the bounded retry policy — ``max_retries``
        attempts with exponential backoff — instead of dying with
        it. A fault with no culprit (whole-dispatch transient)
        requeues every participant. Replaces the old blanket
        ``_fail_all`` for everything short of genuine global errors
        (device loss), which still take that path."""
        self.stats["contained_faults"] += 1
        _metrics()["contained_faults"].inc()
        self.events.append("fault", rid=e.culprit_rid,
                           sid=e.culprit_sid,
                           data={"sids": list(e.sids),
                                 "error": repr(e.original)})
        if self.flight_dir is not None:
            # postmortem bundle while the fault context is still live
            # (probing is lock-free, so holding self._lock is fine)
            obs.dump_flight_bundle(self.flight_dir, "engine-fault",
                                   engine=self)
        # settle trailing readbacks first: a requeued request
        # recomputes from prompt + generated, which must be complete
        self._drain_fetches_locked()
        for sid in sorted(set(e.sids)):
            slot = self.slots[sid] if 0 <= sid < self.S else None
            if slot is None:
                continue       # drain closed it, or already gone
            if sid == e.culprit_sid:
                self._teardown_slot_locked(sid, e.original,
                                           "fault_failed")
            else:
                self._requeue_after_fault_locked(sid, e)

    def _requeue_after_fault_locked(self, sid: int,
                                    e: EngineFault) -> None:
        """Requeue an innocent participant of a faulted dispatch,
        bounded: past ``max_retries`` attempts the request fails too
        (a poisoned batch must not retry forever). Tail of the queue
        — a faulting batch must not starve fresh arrivals — with
        exponential backoff gating re-admission."""
        slot = self.slots[sid]
        req = slot.req
        req.attempts += 1
        if req.attempts > self.max_retries:
            self._teardown_slot_locked(sid, RequestError(
                f"request {req.rid} failed after "
                f"{req.attempts - 1} retries (last fault: "
                f"{e.original!r})"), "retry_exhausted")
            return
        self.slots[sid] = None
        slot.preempted = True     # in-flight rows are recomputed
        self._free_slot_pages_locked(slot, retire=False)
        req.t_earliest = (time.monotonic() + self.retry_backoff_s
                          * (2 ** (req.attempts - 1)))
        self._wait.append(req)
        self.stats["retries"] += 1
        _metrics()["retries"].inc()
        self.events.append("requeue", rid=req.rid, sid=sid,
                           data={"attempts": req.attempts})

    def _plan_steps_locked(self) -> StepPlan:
        """Plan this round with the pure, device-free planner
        (serve/scheduler.py plan_step): which mid-prefill slots
        advance under the shared ``prefill_chunk`` token budget, and
        how many decode steps ride behind them. Run-ahead-to-next-
        completion, quick cadence while admission work is pending,
        and the eos bound all live in the planner — this wrapper only
        snapshots slot state (plus, with speculation on, one
        prompt-lookup proposal per seeded slot)."""
        if self.spec_len:
            self._propose_spec_locked()
        # Stale-frontier depth per slot: decode steps dispatched but
        # not yet read back (the overlapped loop plans BEFORE the
        # trailing drain reveals them). The planner uses it to cap
        # eos-bounded run-ahead so a late-revealed eos discards at
        # most one decode chunk per slot. Identity-checked against
        # the live slot: a freed-and-reseated slot's old rides are
        # not ITS staleness.
        stale = [0] * self.S
        for _buf, riders, steps in self._fetchq:
            for i, slot, _take in riders:
                if 0 <= i < self.S and self.slots[i] is slot:
                    stale[i] += steps
        # owed clamped at 0: an eos-mode rider can overshoot its
        # budget while emission trails, and cancelled/expired slots
        # are torn down before planning ever sees them — the planner
        # contract (serve/scheduler.py) is owed >= 0
        views = [SlotView(sid=i, admit_seq=s.admit_seq,
                          prompt_remaining=s.prefill_remaining,
                          owed=max(0, self._owed(s))
                          if s.cur is not None else 0,
                          seeded=s.cur is not None,
                          spec_drafts=len(s.spec_pending),
                          stale=stale[i],
                          pulling=s.pulling,
                          batch=s.req.batch)
                 for i, s in enumerate(self.slots) if s is not None]
        # Role admission knobs (disaggregation): a prefill replica
        # never runs ahead past one decode chunk, a decode replica's
        # prefill lane shrinks to residual-tail size. Read per round
        # so the pool can re-role a replica between requests.
        caps = role_plan_caps(self.role, page_size=self.Pg,
                              decode_chunk=self.K,
                              prefill_budget=self.PC,
                              max_run_ahead=self.KMAX)
        return plan_step(views, total_slots=self.S,
                         prefill_budget=caps["prefill_budget"],
                         decode_chunk=self.K,
                         max_run_ahead=caps["max_run_ahead"],
                         prefill_batch=self._max_prefill_batch,
                         eos_bounded=self.eos_id is not None,
                         spec_enabled=bool(self.spec_len))

    def _propose_spec_locked(self):
        """Refresh each seeded slot's prompt-lookup proposal. In the
        lockstep loop this runs AFTER the round's full drain, so
        ``req.generated`` is exactly the device's token stream. In
        the overlapped loop it runs from the STALE frontier —
        ``req.generated`` may trail the device by up to one round's
        undrained chunks. That is safe by construction: proposals
        are hints the batched verify re-derives from the true argmax
        (a draft positioned against an outdated context simply gets
        rejected), and the proposer's monotonic-context contract
        (spec_decode.NGramIndex.sync) still holds because
        ``prompt + generated`` only ever grows. The proposer syncs
        its rolling index with the unseen tail and drafts up to
        ``spec_len`` continuation tokens. A slot whose remaining
        budget is 1 proposes nothing — the verify's bonus token
        already covers it."""
        for s in self.slots:
            if s is None:
                continue
            s.spec_pending = []
            if (s.cur is None or s.preempted or s.req.closed
                    or not s.req.generated):
                continue
            if s.spec is None:
                s.spec = self._proposer_factory()
            s.spec.sync(s.req.prompt + s.req.generated)
            room = min(self.spec_len, s.req.remaining - 1)
            if room > 0:
                s.spec_pending = [int(t) for t in s.spec.propose(room)]

    def _owed(self, slot: _Slot) -> int:
        """Decode steps this slot still needs, by dispatch-time
        arithmetic: the prefill emits token 1 of max_new_tokens, every
        ridden step emits one more. Runs AHEAD of emission (which
        trails with the readbacks) — with an eos the true need may be
        less; emission then closes the request early."""
        return slot.req.max_new_tokens - 1 - slot.decoded

    def _retire_planned_locked(self):
        """No-eos mode: free slots whose budget the dispatch just
        consumed — their tokens are still in flight (emission trails)
        but the SCHEDULE is deterministic, so the pages and the slot
        go back to the pool without waiting for a readback."""
        for i, slot in enumerate(self.slots):
            if (slot is not None and slot.cur is not None
                    and self._owed(slot) <= 0):
                self.slots[i] = None
                self._free_slot_pages_locked(slot, retire=True)
                # "completed" counts at request close (emission)

    # ------------------------------------------------------- scheduler

    def _loop(self):
        while True:
            with self._work:
                while (not self._stopped and not self._wait
                       and not any(self.slots)
                       and not self._fetchq
                       and not self._pending_prefill
                       and self._pending_swap is None):
                    # a pending drain-mode weight swap is work: the
                    # settled engine must run one more round so the
                    # flip lands between rounds, not never
                    self._work.wait()
                if self._stopped:
                    # deliver every token already computed before
                    # exiting — retired slots' readbacks still trail;
                    # shutdown() then fails whatever remains in
                    # flight with EngineShutdown
                    self._drain_fetches_locked()
                    return
            try:
                self.step()
            except EngineFault as e:
                # attributable fault outside a dispatch section
                # (defensive — step() normally contains these)
                with self._lock:
                    self._contain_fault_locked(e)
            except BaseException as e:   # global: fail every request
                self._fail_all(e)
                return

    def _fail_all(self, e: BaseException):
        """Global failure (device loss, scheduler bug): every queued
        and in-flight request fails with the error. Attributable
        faults never reach here — they are contained per-slot in
        step() — so this is the path of last resort."""
        self.events.append("fail_all", data={"error": repr(e)})
        if self.flight_dir is not None:
            # the engine is about to lose everything it knows: dump
            # the postmortem BEFORE teardown clears the queues
            obs.dump_flight_bundle(self.flight_dir, "engine-fail-all",
                                   engine=self,
                                   extra={"error": repr(e)})
        with self._lock:
            self.stats["failed_all"] += 1
            failed = set()

            def fail(req):
                if req.closed or id(req) in failed:
                    return
                failed.add(id(req))
                req.closed = True
                req.error = e
                req.out_q.put(_DONE)

            for i, slot in enumerate(self.slots):
                if slot is not None:
                    fail(slot.req)
                    self.slots[i] = None
                    slot.preempted = True
                    self._free_slot_pages_locked(slot, retire=False)
            # retired-at-dispatch requests whose tokens were still in
            # flight live only in the readback queues
            for _buf, riders, _steps in self._fetchq:
                for _i, slot, _t in riders:
                    fail(slot.req)
            for _f, placements in self._pending_prefill:
                for _ix, slot, _row in placements:
                    fail(slot.req)
            self._fetchq.clear()
            self._pending_prefill.clear()
            for req in self._wait:
                fail(req)
            self._wait.clear()
            self._stopped = True

    def _next_admit_locked(self) -> Optional[_Request]:
        """Lane-aware head selection for admission. Drops closed
        requests parked at the head (cancelled/expired while queued
        by a path that left them in place — never admit), then picks
        the first ONLINE request anywhere in the queue: FIFO within
        each lane, but the online lane always outranks batch. Only
        when no online request waits does the batch head admit.

        The chosen request is rotated to the deque FRONT before
        returning, so every existing ``popleft`` admission path
        (plain admission, PULLING admission) stays correct without
        threading an index through."""
        while self._wait and self._wait[0].closed:
            self._wait.popleft()
        if not self._wait:
            return None
        head = self._wait[0]
        if not head.batch:
            return head
        # batch head: any live online request deeper in the queue
        # outranks it (closed entries are skipped in place — they
        # drop when they surface at the head)
        for k in range(1, len(self._wait)):
            r = self._wait[k]
            if r.closed or r.batch:
                continue
            del self._wait[k]
            self._wait.appendleft(r)
            return r
        return head

    def _victim_locked(self, exclude_sid: Optional[int] = None, *,
                       batch_only: bool = False) -> Optional[int]:
        """Preemption victim selection, one policy for every caller:
        the youngest occupied slot, with BATCH slots strictly before
        any online slot (bool sorts False < True, so the key
        ``(batch, admit_seq)`` under ``max`` is batch-first,
        youngest-first within the lane). ``exclude_sid`` protects
        the slot whose growth is hunting (never self-evict); PULLING
        slots are never victims (no pages to reclaim, and a
        background thread owns them). ``batch_only=True`` restricts
        the hunt to batch slots — the online-head admission path,
        where online slots must never be evicted to admit."""
        cands = (j for j, s in enumerate(self.slots)
                 if s is not None and not s.pulling
                 and j != exclude_sid
                 and (s.req.batch or not batch_only))
        return max(cands,
                   key=lambda j: (self.slots[j].req.batch,
                                  self.slots[j].admit_seq),
                   default=None)

    def _admit_locked(self):
        """Chunk-budget admission: a waiting request takes a free
        slot as soon as pages for its FIRST prefill chunk exist —
        not its whole prompt. The prompt then advances chunk by
        chunk in the scheduling rounds (no monolithic padded-batch
        prefill, no same-padded-length grouping: the chunked prefill
        call batches mixed lengths and offsets natively). FIFO:
        admission never reorders past the queue head.

        With the prefix cache on, admission first matches the longest
        cached page-aligned prefix: the slot's page table points at
        those shared pages read-only, prefill RESUMES at the matched
        offset (the existing mid-offset chunked-prefill path), and
        the round's prefill budget only ever pays for the tokens
        actually computed — skipped tokens never enter
        ``prompt_remaining``. A fully-cached prompt copies its final
        matched page into a private page (COW: the model still needs
        the last position's logits to sample the first token, and
        that one-token re-prefill must not scatter into a shared
        page). When the pool is dry, refcount-0 cached pages are
        evicted LRU-first before admission gives up.

        A request carrying a router pull hint (``req.pull``) whose
        prefix is NOT locally cached admits in the PULLING phase
        instead: the slot is seated empty (no pages, no grants, the
        planner skips it) while a background thread pulls the prefix
        KV from the peer replica that advertised it
        (serve/kv_migration.py). Transfer completion inserts the
        pages into the prefix cache and requeues the request at the
        queue FRONT, so the next admission round admits it through
        THIS path as a plain local hit — mid-offset prefill resume,
        COW boundary handling, and hit accounting all unchanged. An
        aborted pull requeues without inserting anything: plain
        prefill, never a wedge.

        Priority lanes: the admitted head is the first ONLINE request
        anywhere in the queue; batch requests admit only when no
        online request waits (FIFO within each lane). When every slot
        is taken and the online head is blocked, the youngest BATCH
        slot is preempted on the spot — online traffic reclaims batch
        capacity slot-by-slot the moment it arrives. While an online
        head waits (for a slot or for pages), the lane order also
        guarantees no batch request can slip past it into capacity it
        frees."""
        if self._pending_swap is not None:
            # drain-mode weight swap pending: admission pauses so the
            # active set settles and the flip can land between rounds
            return
        while self._wait:
            req = self._next_admit_locked()
            if req is None:
                return
            free = [i for i, s in enumerate(self.slots) if s is None]
            if not free:
                if not req.batch:
                    # online head blocked on a full batch: evict the
                    # youngest BATCH slot (recompute / prefix-cache
                    # resume on re-admission — token-identical) and
                    # retry. Online slots are never preempted for
                    # admission.
                    victim = self._victim_locked(None, batch_only=True)
                    if victim is not None:
                        self._preempt_locked(victim)
                        continue
                return
            if req.t_earliest and time.monotonic() < req.t_earliest:
                # retry backoff after a contained fault. FIFO is the
                # admission contract (per lane), so a backing-off
                # head delays everything behind it too.
                return
            prompt = req.recompute_prompt
            if req.pull is not None and self._try_pull_admit_locked(
                    free[0], req, prompt):
                continue       # PULLING slot seated; admit the rest
            shared_pages: List[int] = []
            matched = 0
            copy_src: Optional[int] = None
            if self.prefix_cache is not None:
                shared_pages, matched = self.prefix_cache.match(prompt)
                if matched and matched == len(prompt):
                    # whole prompt cached: re-prefill only the LAST
                    # token, into a private copy of the final page
                    copy_src = shared_pages.pop()
                    matched -= 1
            start = matched
            first = max(1, min(len(prompt) - start, self.PC))
            need = -(-(start + first) // self.Pg) - len(shared_pages)
            page_ids = self._alloc(need)
            if page_ids is None and self.prefix_cache is not None:
                # reclaim LRU refcount-0 cached pages before failing
                if self.prefix_cache.evict(
                        need - self.alloc.n_free) > 0:
                    page_ids = self._alloc(need)
            if page_ids is None:
                # pool dry: hand the matched references back and wait
                if self.prefix_cache is not None:
                    if copy_src is not None:
                        shared_pages = shared_pages + [copy_src]
                    if shared_pages:
                        self.prefix_cache.release(shared_pages)
                return         # wait for completions
            if copy_src is not None:
                # duplicate the boundary page on-stream before any
                # write can target it, then drop the borrowed ref
                self.pages = self._copy_page_fn(
                    self.pages, self._h2d(jnp.int32(copy_src)),
                    self._h2d(jnp.int32(page_ids[0])))
                self.prefix_cache.release([copy_src])
            self._wait.popleft()
            slot = _Slot(req=req, pages=shared_pages + page_ids,
                         pos=start, cur=None,
                         admit_seq=next(self._admit_seq),
                         prompt=prompt, prefilled=start,
                         # re-admission after preemption/fault-requeue:
                         # tokens already delivered count against the
                         # budget, or _owed() over-schedules by that
                         # many steps and run-ahead growth walks past
                         # max_seq_len (and the page-table width)
                         decoded=len(req.generated),
                         shared=len(shared_pages))
            self.slots[free[0]] = slot
            self.stats["admitted"] += 1
            _now = time.monotonic()
            self.events.append("admit", rid=req.rid, sid=free[0],
                               t=_now,
                               data={"cached": start,
                                     "pages": len(slot.pages)})
            if self._obs_enabled and not req.generated \
                    and not req.attempts and not req.preemptions:
                # first admission only: re-admissions after
                # preemption/fault would double-count the wait
                obs.phase_metrics()["queue_wait"].observe(
                    max(0.0, _now - req.t_submit))
            if self.prefix_cache is not None:
                self.prefix_cache.account(start, len(prompt) - start)
                self.stats["cache_hit_tokens"] += start
                self.stats["cache_miss_tokens"] += len(prompt) - start
                if start:
                    self.stats["cache_hit_admissions"] += 1
                    self.events.append("cache_hit", rid=req.rid,
                                       sid=free[0], data=start)

    # -------------------------------------------- KV migration (pull)

    def _try_pull_admit_locked(self, sid: int, req: _Request,
                               prompt: List[int]) -> bool:
        """PULLING admission: seat ``req`` in slot ``sid`` with no
        pages and spawn the background pull its router hint names.
        The hint is consumed EXACTLY ONCE (cleared before any check
        can bail), so no requeue path ever re-pulls. Declines — and
        falls through to normal admission — when no fetcher/cache is
        wired, the hint is empty, or the local tree already covers
        the advertised run (then the pull would buy nothing)."""
        pull = req.pull
        req.pull = None          # consumed exactly once
        if (self.kv_fetcher is None or self.prefix_cache is None
                or req.generated or self._stopped or self._draining):
            return False
        try:
            hashes = [int(h) for h in (pull.get("hashes") or ())]
        except (AttributeError, TypeError, ValueError):
            return False         # malformed hint: plain admission
        if not hashes:
            return False
        have, _ = self.prefix_cache.match_hashes(hashes)
        if have:
            self.prefix_cache.release(have)
        if len(have) >= len(hashes):
            return False         # local cache already covers the hint
        self._wait.popleft()
        slot = _Slot(req=req, pages=[], pos=0, cur=None,
                     admit_seq=next(self._admit_seq), prompt=prompt,
                     prefilled=0, decoded=len(req.generated),
                     pulling=True)
        self.slots[sid] = slot
        self.stats["kv_pull_admissions"] += 1
        self.events.append("pull_start", rid=req.rid, sid=sid,
                           data={"hashes": len(hashes),
                                 "local": len(have)})
        threading.Thread(target=self._run_pull,
                         args=(sid, slot, pull), daemon=True,
                         name=f"kv-pull-{req.rid}").start()
        return True

    def _run_pull(self, sid: int, slot: _Slot,
                  pull: Dict[str, Any]) -> None:
        """Background transfer for one PULLING slot, NO engine lock
        held: the injected fetcher runs the chunked pull protocol
        (kv_migration.pull_prefix — deadline, bounded retries, typed
        abort) against the donor. Landing and requeue happen back
        under the lock; any fetcher escape is an abort, never a
        wedge."""
        payload = None
        try:
            payload = self.kv_fetcher(pull)
        except Exception:
            payload = None
        with self._work:
            self._finish_pull_locked(sid, slot, payload)
            self._work.notify_all()

    def _finish_pull_locked(self, sid: int, slot: _Slot,
                            payload: Optional[Dict[str, Any]]) -> None:
        """Land a finished pull and requeue its request at the FRONT
        of the admission queue: the next ``_admit_locked`` admits it
        through the NORMAL path — a successful landing inserted the
        pulled pages into the prefix cache, so admission matches them
        as a local hit and resumes mid-offset prefill exactly like
        any cached prefix; a failed pull admits as plain prefill
        (fallback counted). Slot identity is validated first: cancel,
        deadline reap, shutdown, or preemption may have torn the slot
        down mid-transfer — the request's fate is already decided and
        this result is dropped."""
        if (self.slots[sid] is not slot or slot.preempted
                or not slot.pulling):
            return
        slot.pulling = False
        self.slots[sid] = None
        req = slot.req
        if req.closed or self._stopped:
            return
        landed = 0
        if payload is not None:
            landed = self._land_pulled_pages_locked(slot.prompt,
                                                    payload)
        if landed:
            self.stats["kv_pull_landed"] += 1
            self.events.append("pull_land", rid=req.rid, sid=sid,
                               data={"pages": landed,
                                     "wire_bytes":
                                         payload.get("wire_bytes", 0)})
        else:
            kv_migration.count_fallback(self.kv_migration_stats)
            self.stats["kv_pull_fallbacks"] += 1
            self.events.append("pull_fallback", rid=req.rid, sid=sid)
        self._wait.appendleft(req)   # front: admit before new arrivals

    def _land_pulled_pages_locked(self, prompt: List[int],
                                  payload: Dict[str, Any]) -> int:
        """Write pulled page payloads into freshly allocated pool
        pages and INSERT them into the prefix cache — the same
        radix-tree insert retirement uses, so refcounts, COW
        discipline, LRU order, and eviction see nothing new. Returns
        pages landed; 0 (mismatched/truncated payload, allocator dry)
        means fall back to plain prefill."""
        if (payload.get("kv_dtype") != self.kv_dtype
                or int(payload.get("page_size") or 0) != self.Pg
                or int(payload.get("n_layers") or 0)
                != self.cfg.n_layers):
            return 0
        n = min(int(payload.get("n_pages") or 0),
                len(prompt) // self.Pg)
        if n <= 0:
            return 0
        try:
            # decode + validate BEFORE allocating: a malformed
            # payload must not cost pool pages
            cols = [page_cols_from_bytes(self.cfg, self.Pg,
                                         self.kv_dtype, blobs)
                    for blobs in payload["pages"][:n]]
        except (ValueError, KeyError, TypeError):
            return 0
        page_ids = self._alloc(n)
        if page_ids is None and self.prefix_cache.evict(
                n - self.alloc.n_free) > 0:
            page_ids = self._alloc(n)
        if page_ids is None:
            return 0
        if self._write_page_fn is None:
            self._write_page_fn = self._build_write_page()
        for dst, page_cols in zip(page_ids, cols):
            self.pages = self._write_page_fn(
                self.pages, self._h2d(jnp.int32(dst)),
                [tuple(self._h2d(c) for c in layer)
                 for layer in page_cols])
        self.prefix_cache.insert(prompt[:n * self.Pg], page_ids, 0)
        self.stats["kv_pulled_pages"] += n
        return n

    def _build_write_page(self):
        """Jitted whole-page landing write: scatter one pulled page's
        per-layer columns (k/v payload and, for int8 pools, their
        per-page scales — they travel together) into physical page
        ``dst`` across every layer. dst is a traced scalar: one
        executable for the whole pull. The donated pool update is the
        same in-place discipline every other jitted step uses."""
        constrain = self._constrain_kv

        def write(pages, dst, cols):
            return constrain(
                [tuple(t.at[:, dst].set(c)
                       for t, c in zip(layer, layer_cols))
                 for layer, layer_cols in zip(pages, cols)])
        return jax.jit(write, donate_argnums=(0,))

    # ------------------------------------------- KV migration (donor)

    def kv_pin_prefix(self, hashes: List[int]) -> List[int]:
        """Donor side of a cross-replica pull: resolve rolling path
        hashes to the longest resident page run and PIN it (refcount
        increment via ``PrefixCache.match_hashes``) so eviction can
        never yank a page mid-transfer. Caller owes one
        ``kv_release_pages`` for the run. Empty when the prefix is
        gone or the engine is stopped/draining — the KVDonor turns
        that into a typed ``KVPullAborted``."""
        with self._lock:
            if (self.prefix_cache is None or self._stopped
                    or self._draining):
                return []
            pages, _ = self.prefix_cache.match_hashes(hashes)
            return pages

    def kv_export_pages(self, pages: List[int]) -> List[Any]:
        """Raw bytes of pinned pages, per page per layer (int8 scales
        ride along — models/kv_cache.export_page_bytes). Under the
        engine lock: pool buffers are donated to jitted calls, so an
        unlocked read could touch an invalidated buffer mid-round.
        A stopped donor refuses with the typed abort — in-process
        pools must mirror what a dead peer process looks like over
        the socket, or chaos kills would "succeed" off a corpse."""
        with self._lock:
            if self._stopped:
                raise kv_migration.KVPullAborted(
                    "donor engine stopped mid-transfer")
            return [export_page_bytes(self.pages, int(p))
                    for p in pages]

    def kv_release_pages(self, pages: List[int]) -> None:
        """Unpin a transfer's pages (drop the match_hashes refs)."""
        with self._lock:
            if self.prefix_cache is not None and pages:
                self.prefix_cache.release(list(pages))

    def _dispatch_prefill_locked(self, grants):
        """Execute this round's prefill grants: grow each granted
        slot's pages to cover its chunk (evicting the youngest OTHER
        slot — batch lane first — when the pool runs dry, exactly
        like decode growth),
        then dispatch ONE batched chunked-prefill call for every
        surviving grant. Rows carry independent start offsets and
        lengths, so mixed prompt lengths and mid-prompt resumptions
        batch together."""
        rows = []
        for g in grants:
            slot = self.slots[g.sid]
            if slot is None:
                continue       # evicted by an earlier grant's growth
            take = min(g.tokens, slot.prefill_remaining)
            if take <= 0:
                continue
            self._fire("dispatch_prefill", sid=g.sid,
                       rid=slot.req.rid)
            self._check_cow_locked(slot, slot.prefilled)
            need = -(-(slot.prefilled + take) // self.Pg)
            evicted = False
            while len(slot.pages) < need:
                if self.slots[g.sid] is not slot:
                    evicted = True
                    break
                got = self._alloc(need - len(slot.pages))
                if got is not None:
                    slot.pages.extend(got)
                    break
                if (self.prefix_cache is not None
                        and self.prefix_cache.evict(
                            need - len(slot.pages)
                            - self.alloc.n_free) > 0):
                    continue    # reclaimed cached pages; retry alloc
                victim = self._victim_locked(g.sid)
                if victim is None:
                    # alone and still can't grow — attributable to
                    # THIS request: contained, not _fail_all
                    raise EngineFault(RequestError(
                        f"request {slot.req.rid}: page pool "
                        f"exhausted by one slot"),
                        culprit_sid=g.sid, culprit_rid=slot.req.rid)
                self._preempt_locked(victim)
            if not evicted and self.slots[g.sid] is slot:
                rows.append((g.sid, slot, take))
        # a LATER grant's growth can evict an EARLIER grant's slot
        # (victim choice is global youngest) — refilter before dispatch
        rows = [(ix, slot, take) for ix, slot, take in rows
                if self.slots[ix] is slot]
        if self._stopped:
            return     # force-killed mid-loop (zombie fence): the
                       # released thread must not dispatch
        if rows:
            self._prefill_batch(rows)

    def _grow_or_preempt_locked(self, steps: int):
        """Ensure every active slot's pages cover this dispatch's
        writes; evict the youngest slots (batch lane first) if the
        pool runs dry."""
        for i in sorted(
                (i for i, s in enumerate(self.slots) if s is not None),
                key=lambda i: self.slots[i].admit_seq):
            slot = self.slots[i]
            if slot is None:        # evicted by an elder slot's growth
                continue
            if slot.cur is None:
                continue        # not riding this dispatch (seed not
                                # yet scattered): writes nothing
            eff = min(steps, max(1, self._owed(slot)))
            need = -(-(slot.pos + eff) // self.Pg)
            while len(slot.pages) < need:
                if self.slots[i] is not slot:
                    # a preemption's drain closed THIS slot (eos /
                    # budget in a trailing readback); growing the
                    # detached object would leak its new pages
                    break
                got = self._alloc(need - len(slot.pages))
                if got is not None:
                    slot.pages.extend(got)
                    break
                if (self.prefix_cache is not None
                        and self.prefix_cache.evict(
                            need - len(slot.pages)
                            - self.alloc.n_free) > 0):
                    continue    # reclaimed cached pages; retry alloc
                victim = self._victim_locked(i)
                if victim is None:
                    # alone and still can't grow — attributable to
                    # THIS request: contained, not _fail_all
                    raise EngineFault(RequestError(
                        f"request {slot.req.rid}: page pool "
                        f"exhausted by one slot"),
                        culprit_sid=i, culprit_rid=slot.req.rid)
                self._preempt_locked(victim)

    def _check_cow_locked(self, slot: _Slot, write_pos: int) -> None:
        """Copy-on-write invariant: pool pages are donated to jitted
        calls and scattered into IN PLACE, so a write may only ever
        target a page the slot exclusively owns. Shared (cache-owned)
        pages are the slot's leading ``slot.shared`` page-table
        entries and must sit strictly behind the write frontier."""
        if slot.shared and write_pos < slot.shared * self.Pg:
            raise RuntimeError(
                f"COW violation: slot for rid={slot.req.rid} would "
                f"scatter at pos {write_pos} into shared page index "
                f"{write_pos // self.Pg} (< {slot.shared} cache-owned "
                f"pages)")

    def _free_slot_pages_locked(self, slot: _Slot,
                                *, retire: bool) -> None:
        """Return a slot's pages. Without the prefix cache this is a
        plain free. With it: shared pages only ever drop a reference
        (the tree keeps the KV); on retirement the finished prompt's
        full pages are INSERTED into the radix tree instead of freed
        (private ones donated, shared ones deduped), and only the
        boundary/generated tail goes back to the allocator."""
        if self.prefix_cache is None:
            self.alloc.free(slot.pages)
            return
        if retire and self._force_killed:
            # zombie fence: a force-killed engine's late retirement
            # must not publish pages into the prefix cache — drop
            # shared references and free private pages instead
            retire = False
        if retire:
            n_full = min(len(slot.prompt) // self.Pg, len(slot.pages))
            self.prefix_cache.insert(slot.prompt,
                                     slot.pages[:n_full], slot.shared)
            tail = slot.pages[n_full:]
            if tail:
                self.alloc.free(tail)
        else:
            self.prefix_cache.release(slot.pages[:slot.shared])
            priv = slot.pages[slot.shared:]
            if priv:
                self.alloc.free(priv)

    def prefix_stats(self) -> Optional[Dict[str, Any]]:
        """Prefix-cache counters (None when the cache is off)."""
        if self.prefix_cache is None:
            return None
        with self._lock:
            return self.prefix_cache.stats()

    def _preempt_locked(self, ix: int):
        # The victim's generated-so-far must be complete before the
        # recompute prompt is frozen: drain every trailing readback
        # (rare path — preemption already pays a full re-prefill).
        victim = self.slots[ix]
        self._drain_fetches_locked()
        if self.slots[ix] is not victim:
            # the drain closed the victim (eos / budget in a trailing
            # readback): its pages are already freed — nothing to evict
            return
        slot = victim
        self.slots[ix] = None
        slot.preempted = True     # in-flight rows are recomputed
        # retire=False: a preemption must NEVER free shared pages —
        # other sequences' page tables may point at them; their
        # references are dropped and the tree keeps the KV
        self._free_slot_pages_locked(slot, retire=False)
        slot.req.preemptions += 1
        self.stats["preemptions"] += 1
        if slot.req.batch:
            self.stats["batch_preemptions"] += 1
            _metrics()["batch_preempted"].inc()
        self.events.append("preempt", rid=slot.req.rid, sid=ix,
                           data={"preemptions": slot.req.preemptions,
                                 "lane": (LANE_BATCH if slot.req.batch
                                          else LANE_ONLINE)})
        self._wait.appendleft(slot.req)   # front: re-admit first

    def _dispatch_chunk_locked(self, steps: int):
        """Launch one decode dispatch of ``steps`` steps
        asynchronously. The full carry — pages, per-slot write
        position, per-slot next-token — lives on device and chains
        into the next dispatch; the host ships only the page table.
        The token buffer joins the trailing readback queue. ``steps``
        is a runtime scalar to the jitted fori_loop — no recompile
        per value."""
        pt = np.zeros((self.S, self.max_pages), np.int32)
        riders = []
        for i, slot in enumerate(self.slots):
            if slot is None or slot.cur is None:
                continue
            self._fire("dispatch_decode", sid=i, rid=slot.req.rid)
            self._check_cow_locked(slot, slot.pos)
            pt[i, :len(slot.pages)] = slot.pages
            # tokens this slot still owes its client from THIS
            # dispatch (the tail of an overshooting window is junk)
            take = min(steps, max(0, self._owed(slot)))
            riders.append((i, slot, take))
        if not riders or self._stopped:
            # every planned rider was preempted by this round's
            # prefill growth — an empty dispatch would decode junk —
            # or the engine was force-killed mid-loop (zombie fence)
            return
        (toks, self.pages, self._rng, self._dev_pos,
         self._dev_cur) = self._decode_fn(
            self.params, self.pages, self._h2d(pt),
            self._dev_pos, self._dev_cur, self._rng,
            self._h2d(jnp.int32(steps)))
        # host mirrors advance NOW; emission trails
        for _i, slot, _t in riders:
            slot.pos += steps
            slot.decoded += steps
        self._fetchq.append((toks, riders, steps))
        self.events.append("decode", data=steps)
        self.stats["chunks"] += 1
        self.stats["decode_steps"] += steps
        self._hb = time.monotonic()   # dispatch completed: progress

    def _dispatch_spec_locked(self, grants):
        """One batched draft-and-verify dispatch (speculative
        decoding, serve/spec_decode.py). Every granted slot's row is
        ``[cur, d_1 .. d_k]`` — its last emitted token plus up to
        ``spec_len`` prompt-lookup drafts — scored in ONE forward
        pass through the paged multi-token branch at the slot's own
        offset (the same append-at-offset path chunked prefill uses).
        Row i's argmax at position j is the true greedy token after
        its j-th input token, so the longest draft prefix matching
        the argmax is accepted, plus the argmax after it (bonus
        token): between 1 and k+1 tokens per slot per dispatch, each
        one EXACTLY what non-speculative greedy decode would have
        produced.

        Rollback is free: the verify scattered KV for every input
        token, but positions past the accepted frontier hold tokens
        the model rejected — the slot's write offset is CLAMPED to
        ``pos + accepted + 1`` and the garbage beyond it is
        overwritten by later dispatches before any query's causal
        window can reach it (a key at position p is only attended
        once some query sits at >= p, and every later dispatch
        rewrites positions from the clamped frontier up before
        attending). Pages stay owned by the slot. COW discipline
        from the prefix cache is asserted per row: the verify writes
        from ``slot.pos``, which page-aligned matching keeps
        strictly past the shared (refcounted) pages, so
        verification never scatters into a page another sequence
        reads.

        Host-synchronous by construction: acceptance decides the
        next dispatch's input token and offset, so the argmax
        readback blocks here (spec trades the deferred pipeline's
        async pacing for multi-token dispatches). Under the
        overlapped loop the round's planning ran from the stale
        frontier, so the TRUE frontier is settled HERE instead —
        the verify's row 0 is ``generated[-1]``, which must be the
        device's latest token, not the host mirror's."""
        if self.overlap:
            # settle every trailing readback before freezing rows:
            # drafts proposed against the stale frontier are mere
            # hints (a mispositioned draft just gets rejected), but
            # the verify INPUT must be exact. This blocks only in
            # spec mode — the plain decode/prefill lanes never pay
            # it.
            self._drain_fetches_locked()
        T = self.spec_len + 1
        if self._verify_fn is None:
            self._verify_fn = self._build_verify(T)
        rows = []
        for g in grants:
            slot = self.slots[g.sid]
            if (slot is None or slot.cur is None
                    or not slot.req.generated):
                continue       # evicted / reseated since planning
            drafts = slot.spec_pending[:max(0, g.drafts)]
            self._fire("dispatch_spec", sid=g.sid, rid=slot.req.rid)
            self._check_cow_locked(slot, slot.pos)
            # grow pages to cover every verify write (cur + drafts),
            # exactly like prefill growth: prefix-cache eviction
            # first, then youngest-other (batch-first) preemption
            need = -(-(slot.pos + len(drafts) + 1) // self.Pg)
            evicted = False
            while len(slot.pages) < need:
                if self.slots[g.sid] is not slot:
                    evicted = True
                    break
                got = self._alloc(need - len(slot.pages))
                if got is not None:
                    slot.pages.extend(got)
                    break
                if (self.prefix_cache is not None
                        and self.prefix_cache.evict(
                            need - len(slot.pages)
                            - self.alloc.n_free) > 0):
                    continue
                victim = self._victim_locked(g.sid)
                if victim is None:
                    # submit() sized the pool for prompt+completion,
                    # and pos + drafts + 1 never exceeds that —
                    # attributable to THIS request, so contained
                    raise EngineFault(RequestError(
                        f"request {slot.req.rid}: page pool "
                        f"exhausted by one slot"),
                        culprit_sid=g.sid, culprit_rid=slot.req.rid)
                self._preempt_locked(victim)
            if not evicted and self.slots[g.sid] is slot:
                rows.append((g.sid, slot, drafts))
        # a later grant's growth can evict an earlier grant's slot
        rows = [(ix, slot, d) for ix, slot, d in rows
                if self.slots[ix] is slot]
        if not rows or self._stopped:
            return     # nothing to verify, or force-killed mid-loop
        ids = np.zeros((self.S, T), np.int32)
        start = np.zeros((self.S,), np.int32)
        pt = np.zeros((self.S, self.max_pages), np.int32)
        for i, slot, drafts in rows:
            ids[i, 0] = slot.req.generated[-1]
            if drafts:
                ids[i, 1:1 + len(drafts)] = drafts
            start[i] = slot.pos
            pt[i, :len(slot.pages)] = slot.pages
        out_dev, self.pages = self._verify_fn(
            self.params, self.pages, self._h2d(ids),
            self._h2d(start), self._h2d(pt))
        out = np.asarray(out_dev)    # host sync: acceptance gates
        self._hb = time.monotonic()  # verify completed: progress
        m = spec_decode.metrics()
        self.stats["spec_rounds"] += 1
        # surviving slots' device decode state is reseeded with the
        # accepted frontier via the admission scatter (mode='drop'
        # rows padded with ix == S)
        ixs = np.full((self.S,), self.S, np.int32)
        toks = np.zeros((self.S,), np.int32)
        posv = np.zeros((self.S,), np.int32)
        n_seed = 0
        for i, slot, drafts in rows:
            row = out[i]
            a = 0
            while a < len(drafts) and drafts[a] == int(row[a]):
                a += 1
            produced = a + 1
            proposed = len(drafts)
            self.events.append("spec", rid=slot.req.rid, sid=i,
                               data=(proposed, a))
            self.stats["spec_riders"] += 1
            self.stats["spec_proposed"] += proposed
            self.stats["spec_accepted"] += a
            self.stats["spec_rejected"] += proposed - a
            self.stats["spec_tokens"] += produced
            if proposed:
                m["proposed"].inc(proposed)
                if a:
                    m["accepted"].inc(a)
                if proposed - a:
                    m["rejected"].inc(proposed - a)
                m["accept_rate"].observe(a / proposed)
            slot.spec_pending = []
            slot.pos += produced       # rollback clamp: KV frontier
            slot.decoded += produced   # = accepted + bonus, not k+1
            self._emit_to(slot.req, [int(t) for t in row[:produced]],
                          i)
            if self.slots[i] is slot:  # not closed by the emission
                ixs[n_seed] = i
                toks[n_seed] = int(row[a])
                posv[n_seed] = slot.pos
                n_seed += 1
        if n_seed:
            self._dev_cur, self._dev_pos = self._seed_fn(
                self._dev_cur, self._dev_pos, self._h2d(toks),
                self._h2d(ixs),
                self._h2d(jnp.arange(self.S, dtype=jnp.int32)),
                self._h2d(posv))

    def spec_stats(self) -> Optional[Dict[str, Any]]:
        """Speculative-decoding counters (None when speculation is
        off). ``tokens_per_dispatch`` is emitted tokens per
        (slot, verify-dispatch) ride — > 1.0 means speculation beat
        the one-token-per-forward-pass decode floor."""
        if not self.spec_len:
            return None
        with self._lock:
            s = self.stats
            proposed = s["spec_proposed"]
            riders = s["spec_riders"]
            return {
                "spec_len": self.spec_len,
                "spec_ngram": self.spec_ngram,
                "rounds": s["spec_rounds"],
                "proposed_tokens": proposed,
                "accepted_tokens": s["spec_accepted"],
                "rejected_tokens": s["spec_rejected"],
                "accept_rate": round(s["spec_accepted"] / proposed, 4)
                if proposed else 0.0,
                "tokens_per_dispatch":
                    round(s["spec_tokens"] / riders, 4)
                    if riders else 0.0,
            }

    def _drain_fetches_locked(self, limit: Optional[int] = None,
                              keep: int = 0,
                              ready_only: bool = False):
        """Trailing token readback: fetch up to ``limit`` outstanding
        decode buffers (None = all) plus EVERY in-flight prefill's
        firsts in one host sync each round, and emit to clients.
        Blocking here never stalls the device — the next dispatch is
        already queued behind the one being read."""
        blocking_rounds = 0
        while self._fetchq or self._pending_prefill:
            front_ready = bool(self._fetchq) and \
                _dev_ready(_first_leaf(self._fetchq[0][0]))
            # A finished buffer is always read (free — no block): on a
            # local device the previous dispatch is usually done by
            # now, so emission stays prompt. The `keep` fence only
            # protects STILL-COMPUTING dispatches — blocking on the
            # one just queued would serialize fetch after compute.
            take_buf = bool(self._fetchq) and (
                front_ready or
                (not ready_only and len(self._fetchq) > keep))
            # Prefill firsts ride along unless this is a ready-only
            # sweep and any of them is still computing (a sweep must
            # never block). Ordering stays safe: a rider's prefill is
            # always older than its first decode buffer, so a READY
            # front implies its riders' firsts are ready too — only
            # NEWER prefills (whose slots ride no fetched buffer yet)
            # can be withheld.
            pre_ready = bool(self._pending_prefill) and (
                not ready_only or all(
                    _dev_ready(_first_leaf(f))
                    for f, _ in self._pending_prefill))
            if not take_buf and not pre_ready:
                return
            if take_buf and not front_ready:
                if limit is not None and blocking_rounds >= limit:
                    return
                blocking_rounds += 1
            batch = []
            if take_buf:
                batch.append(self._fetchq.popleft())
            pend_pre = []
            if pre_ready:
                pend_pre, self._pending_prefill = \
                    self._pending_prefill, []
            _t_rb = time.monotonic()
            # Touch the heartbeat BEFORE the blocking get as well as
            # after: a drain working through several buffers blocks
            # once per buffer, and each iteration boundary is real
            # progress — without the pre-get touch a slow-but-moving
            # multi-buffer readback under load reads as one long
            # stall and rides the watchdog ladder to SUSPECT/WEDGED
            # (serve/watchdog.py judges heartbeat AGE, not activity).
            self._hb = _t_rb
            vals = jax.device_get(
                [b[0] for b in batch] + [f for f, _ in pend_pre])
            self._hb = time.monotonic()   # readback completed
            self.events.append(
                "readback",
                data={"bufs": len(batch) + len(pend_pre)})
            if self._obs_enabled:
                obs.phase_metrics()["readback"].observe(
                    self._hb - _t_rb)
            k = len(batch)
            # prefill firsts FIRST: a slot's seeding prefill always
            # precedes its first decode ride, and both can land in
            # the same drain round
            for (_f, placements), firsts in zip(pend_pre, vals[k:]):
                f_lps = None
                if isinstance(firsts, tuple):   # logprob capture
                    firsts, f_lps = firsts
                for ix, slot, row in placements:
                    if slot.preempted:
                        continue
                    try:
                        self._fire("readback", sid=ix,
                                   rid=slot.req.rid)
                    except EngineFault as e:
                        self._fail_rider_locked(ix, slot, e.original)
                        continue
                    self._emit_to(slot.req, [int(firsts[row])], ix,
                                  lps=(None if f_lps is None
                                       else [float(f_lps[row])]))
            for (_buf, riders, _steps), toks in zip(batch, vals):
                lp_buf = None
                if isinstance(toks, tuple):     # logprob capture
                    toks, lp_buf = toks
                for i, slot, take in riders:
                    if slot.preempted:
                        continue    # recomputed from scratch
                    try:
                        self._fire("readback", sid=i,
                                   rid=slot.req.rid)
                    except EngineFault as e:
                        self._fail_rider_locked(i, slot, e.original)
                        continue
                    self._emit_to(slot.req, toks[:take, i].tolist(), i,
                                  lps=(None if lp_buf is None
                                       else lp_buf[:take, i].tolist()))

    def _fail_rider_locked(self, ix: int, slot: _Slot,
                           err: BaseException) -> None:
        """A fault while emitting ONE rider's tokens (readback/
        emission path) fails only that request: its slot — if still
        live; no-eos mode retires slots at dispatch time — is torn
        down, every other rider's emission proceeds untouched."""
        self.stats["contained_faults"] += 1
        _metrics()["contained_faults"].inc()
        if self.slots[ix] is slot and not slot.preempted:
            self._teardown_slot_locked(ix, err, "fault_failed")
        else:
            self._fail_req_locked(slot.req, err, "fault_failed")

    def _emit_to(self, req: _Request, tokens: List[int], ix: int,
                 lps: Optional[List[float]] = None):
        """Deliver tokens to the request; close it when it hits eos
        or its budget. In no-eos mode the slot/pages were already
        retired at dispatch time; with an eos, closing here frees
        them (the readback is what reveals the eos). ``lps`` (logprob
        capture) is index-aligned with ``tokens``; exactly the
        emitted prefix is appended, so eos/budget truncation keeps
        ``req.logprobs`` aligned with ``req.generated``."""
        if req.closed:
            return
        done = False
        n_put = 0
        for t in tokens:
            t = int(t)
            if req.t_first is None:
                # TTFT is stamped HERE — the moment the token reaches
                # the request stream — not when a later decode chunk
                # drains (the accounting bug the r05 bench carried)
                req.t_first = time.monotonic()
                ttft = req.t_first - req.t_submit
                if not req.batch:
                    # online SLO signals only: a batch request has no
                    # TTFT SLO (it may sit queued for hours by
                    # design), and folding its wait into ttfts_s /
                    # the EWMA would poison the autoscaler's latency
                    # signal and every bench percentile
                    self.ttfts_s.append(ttft)
                    a = self._ttft_ewma_alpha
                    self._ttft_ewma = ttft if self._ttft_ewma is None \
                        else a * ttft + (1 - a) * self._ttft_ewma
                self.events.append("first_token", rid=req.rid,
                                   sid=ix, t=req.t_first,
                                   data={"ttft_s": ttft,
                                         "lane": (LANE_BATCH
                                                  if req.batch
                                                  else LANE_ONLINE)})
                if self._obs_enabled and not req.batch:
                    obs.phase_metrics()["ttft"].observe(ttft)
            req.generated.append(t)
            req.out_q.put(t)
            n_put += 1
            if ((self.eos_id is not None and t == self.eos_id)
                    or req.remaining <= 0):
                done = True
                break
        if n_put and req.logprobs is not None and lps is not None:
            req.logprobs.extend(float(x) for x in lps[:n_put])
        if n_put:
            _now = time.monotonic()
            self.events.append("emit", rid=req.rid, sid=ix, t=_now,
                               data={"n": n_put})
            if req.batch:
                self.stats["batch_tokens"] += n_put
                _metrics()["batch_tokens"].inc(n_put)
            if req.t_last_emit is not None:
                # mean gap per token over this readback batch
                gap = max(0.0, _now - req.t_last_emit) / n_put
                if self._obs_enabled:
                    obs.phase_metrics()["inter_token"].observe(gap)
                if not req.batch:
                    # online lane only, like the TTFT EWMA: batch
                    # streams run at whatever cadence the backlog
                    # allows and would drown the decode pool's
                    # latency signal
                    a = self._itl_ewma_alpha
                    self._itl_ewma = gap if self._itl_ewma is None \
                        else a * gap + (1 - a) * self._itl_ewma
            req.t_last_emit = _now
        if done:
            req.closed = True
            slot = self.slots[ix]
            if slot is not None and slot.req is req:
                self.slots[ix] = None
                self._free_slot_pages_locked(slot, retire=True)
            self.stats["completed"] += 1
            self.events.append("retire", rid=req.rid, sid=ix,
                               data={"generated": len(req.generated)})
            req.out_q.put(_DONE)

    # ----------------------------------------------------- jitted fns

    def _prefill_batch(self, rows) -> None:
        """Dispatch ONE chunked-prefill call advancing up to
        ``_max_prefill_batch`` slots' prompts by their granted
        lengths. rows: [(slot index, slot, take), ...].

        Each row appends ``take`` prompt tokens AT ITS OWN OFFSET
        into its own pages (the paged-KV append-at-offset path:
        chunks start mid-page and span pages), so mixed lengths,
        mixed offsets, and resumed prompts share one executable —
        the old path compiled one executable per padded prompt
        length, measured as multi-second p99 stalls on cache misses.
        The chunk width is bucketed to a power of two (floor
        page_size, cap prefill_chunk): a handful of variants total.
        Rows whose chunk ENDS the prompt sample the request's first
        token from the chunk logits; it is seeded into the device
        decode state with an on-stream scatter (no host sync) and
        queued for emission at the next trailing readback — the
        first streamed token goes out at end-of-prompt-prefill,
        never after a decode-chunk drain. Unused batch rows point at
        the null page and are dropped by the seed scatter."""
        B = self._max_prefill_batch
        mx = max(take for _ix, _s, take in rows)
        T = max(1, min(self.PC, self.Pg))
        while T < mx:
            T *= 2
        T = min(T, self.PC)
        fn = self._prefill_cache.get(T)
        if fn is None:
            fn = self._build_prefill(T)
            self._prefill_cache[T] = fn
            while len(self._prefill_cache) > self._max_prefill_compiles:
                self._prefill_cache.popitem(last=False)
        self._prefill_cache.move_to_end(T)
        ids = np.zeros((B, T), np.int32)
        start = np.zeros((B,), np.int32)
        last_idx = np.zeros((B,), np.int32)
        pt = np.zeros((B, self.max_pages), np.int32)  # dummies -> null
        for r, (_ix, slot, take) in enumerate(rows):
            ids[r, :take] = slot.prompt[
                slot.prefilled:slot.prefilled + take]
            start[r] = slot.prefilled
            last_idx[r] = take - 1
            pt[r, :len(slot.pages)] = slot.pages
        out, self.pages, self._rng = fn(
            self.params, self.pages, self._h2d(ids),
            self._h2d(start), self._h2d(last_idx),
            self._h2d(pt), self._rng)
        # logprob capture packs (firsts, first_logprobs); the seed
        # scatter takes the raw firsts, emission gets the pair
        firsts = out[0] if self.capture_logprobs else out
        placements = []
        for r, (ix, slot, take) in enumerate(rows):
            slot.prefilled += take
            slot.pos = slot.prefilled
            if slot.prefill_remaining == 0:
                placements.append((ix, slot, r))
        # Seed the device decode state for rows that FINISHED their
        # prompt WITHOUT a host sync: scatter firsts/positions into
        # dev_cur/dev_pos rows on-stream, after which the slots ride
        # the very next decode dispatch.
        ixs = np.full((B,), self.S, np.int32)   # S = dropped row
        rws = np.zeros((B,), np.int32)
        posv = np.zeros((B,), np.int32)
        for r, (ix, slot, row) in enumerate(placements):
            ixs[r], rws[r], posv[r] = ix, row, slot.pos
        self._dev_cur, self._dev_pos = self._seed_fn(
            self._dev_cur, self._dev_pos, firsts,
            self._h2d(ixs), self._h2d(rws), self._h2d(posv))
        for ix, slot, _row in placements:
            slot.cur = -1      # device-seeded: ridable
        # firsts also stays on device for EMISSION: its readback
        # rides the next trailing sync, so prefill never stalls the
        # decode stream on a host RTT. Queued even with no finished
        # rows so drains (and preemption barriers) can sync on every
        # in-flight prefill dispatch.
        self._pending_prefill.append((out, placements))
        self.events.append(
            "prefill",
            rid=tuple(slot.req.rid for _ix, slot, _t in rows),
            data=tuple((ix, take) for ix, _s, take in rows))
        self.stats["prefills"] += 1
        self.stats["prefill_tokens"] += sum(
            take for _ix, _s, take in rows)
        self.stats["prefilled_seqs"] += len(placements)
        self._hb = time.monotonic()   # dispatch completed: a long
                                      # prompt prefilling chunk by
                                      # chunk is moving, not wedged

    def _build_prefill(self, T: int):
        """One chunked-prefill executable for chunk width ``T``:
        [B, T] token ids at per-row start offsets scatter into the
        rows' pages (append-at-offset) and attend causally over each
        row's own page window. The row's last real position samples
        a candidate first token — junk for rows mid-prompt, consumed
        only for rows that just finished their prompt."""
        model, temp = self.model, self.temperature
        B = self._max_prefill_batch
        constrain = self._constrain_kv
        capture = self.capture_logprobs
        from ray_tpu.models.llama import _pick_token

        def prefill(params, pages, ids, start, last_idx, page_table,
                    rng):
            rng, sub = jax.random.split(rng)
            # kv_layer_view/store keep this builder dtype-agnostic:
            # fp layers are (pk, pv), int8 layers (pk, pv, sk, sv) —
            # the scales ride the same donated tuple through the step
            kv = [kv_layer_view(layer, page_table) for layer in pages]
            logits, new_kv = model.apply(params, ids, kv_caches=kv,
                                         cache_len=start)
            new_pages = constrain([kv_layer_store(c) for c in new_kv])
            last = logits[jnp.arange(B), last_idx]        # [B, V]
            firsts = _pick_token(last, sub, temp)
            if capture:
                # Score under the SAMPLING distribution (temperature-
                # scaled at temp > 0) — the behavior policy an RL
                # learner's importance ratio needs, not the raw model
                # distribution.
                slog = (last.astype(jnp.float32) / temp if temp > 0.0
                        else last.astype(jnp.float32))
                lp = jnp.take_along_axis(
                    jax.nn.log_softmax(slog),
                    firsts[:, None], axis=-1)[:, 0]
                return (firsts, lp), new_pages, rng
            return firsts, new_pages, rng

        return jax.jit(prefill, donate_argnums=(1,))

    def _build_verify(self, T: int):
        """One spec-verify executable for row width ``T`` (=
        ``spec_len + 1``): [S, T] rows of [cur, drafts...] scatter
        into each slot's pages at its own offset and attend causally
        over the slot's page window — the exact chunked-prefill path,
        reused at decode offsets. Greedy by construction: position
        j's argmax is the token plain temperature-0 decode would
        have emitted after input j, so acceptance is a pure prefix
        compare on the host. No rng threading — speculation is
        disabled at temperature > 0."""
        model = self.model
        constrain = self._constrain_kv

        def verify(params, pages, ids, start, page_table):
            kv = [kv_layer_view(layer, page_table) for layer in pages]
            logits, new_kv = model.apply(params, ids, kv_caches=kv,
                                         cache_len=start)
            new_pages = constrain([kv_layer_store(c) for c in new_kv])
            return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
                    new_pages)

        return jax.jit(verify, donate_argnums=(1,))

    def _build_decode(self):
        model, temp = self.model, self.temperature
        KMAX, S = self.KMAX, self.S
        constrain = self._constrain_kv
        capture = self.capture_logprobs
        from ray_tpu.models.llama import _pick_token

        def decode(params, pages, page_table, pos, cur, rng, steps):
            # fori_loop with a RUNTIME bound: one executable serves
            # every dispatch length (chunk-sized quick syncs and full
            # run-ahead alike); tokens land in a fixed [KMAX, S]
            # buffer, rows past `steps` stay zero and are never read.
            # pos/cur are the DEVICE-authoritative per-slot state:
            # they chain dispatch-to-dispatch (admission seeds rows
            # via _build_seed's scatter), so no host readback ever
            # sits between two dispatches. With logprob capture a
            # float32 [KMAX, S] buffer of the chosen tokens' logprobs
            # rides the same carry and the same trailing readback.
            buf0 = jnp.zeros((KMAX, S), jnp.int32)
            lp0 = jnp.zeros((KMAX, S), jnp.float32)

            def body(i, carry):
                pages, pos, cur, key, buf, lps = carry
                key, sub = jax.random.split(key)
                kv = [kv_layer_view(layer, page_table)
                      for layer in pages]
                logits, new_kv = model.apply(
                    params, cur[:, None], kv_caches=kv, cache_len=pos)
                nxt = _pick_token(logits[:, -1], sub, temp)
                if capture:
                    # Behavior-policy logprob: temperature-scaled to
                    # match what _pick_token actually sampled from.
                    slog = (logits[:, -1].astype(jnp.float32) / temp
                            if temp > 0.0
                            else logits[:, -1].astype(jnp.float32))
                    lp = jnp.take_along_axis(
                        jax.nn.log_softmax(slog),
                        nxt[:, None], axis=-1)[:, 0]
                    lps = lps.at[i].set(lp)
                # pin the loop-carried pool to the head-sharded layout
                # so the carry's sharding is loop-invariant (GSPMD
                # would otherwise be free to reshard mid-carry)
                new_pages = constrain(
                    [kv_layer_store(c) for c in new_kv])
                return (new_pages, pos + 1, nxt, key,
                        buf.at[i].set(nxt), lps)
            pages, pos, cur, key, buf, lps = jax.lax.fori_loop(
                0, steps, body, (pages, pos, cur, rng, buf0, lp0))
            # key/pos/cur return as device state: the host never syncs
            # on them between dispatches
            out = (buf, lps) if capture else buf
            return out, pages, key, pos, cur   # buf: [KMAX, S]

        return jax.jit(decode, donate_argnums=(1, 3, 4))

    def _build_copy_page(self):
        """Jitted whole-page copy across every layer's K and V pool:
        the prefix cache's one COW copy, used when an admission's
        prompt is FULLY cached — the final matched page is duplicated
        into a private page so the one-token re-prefill (the model
        needs the last position's logits) never scatters into a
        shared page. src/dst are traced scalars: one executable.
        Under tensor parallelism the copy stays device-local: axis 0
        (the sharded kv-head axis) is untouched, each device
        duplicates its own head shard of the page."""
        constrain = self._constrain_kv

        def copy(pages, src, dst):
            # int8 layers are 4-tuples whose trailing scale tensors
            # copy their (rank-3) page column the same way — COW gets
            # the page's quantization scale for free, so a COW'd page
            # dequantizes identically to its source
            return constrain([tuple(t.at[:, dst].set(t[:, src])
                                    for t in layer)
                              for layer in pages])
        return jax.jit(copy, donate_argnums=(0,))

    def _build_seed(self):
        """Jitted admission seeding: scatter a prefill batch's first
        tokens and write positions into the device decode state.
        Rows padded with ix == S drop (mode='drop') — one executable
        regardless of how many slots the group filled."""
        def seed(dev_cur, dev_pos, firsts, ixs, rows, posv):
            return (dev_cur.at[ixs].set(firsts[rows], mode="drop"),
                    dev_pos.at[ixs].set(posv, mode="drop"))
        return jax.jit(seed, donate_argnums=(0, 1))
