"""Continuous-batching LLM engine with a paged KV cache.

Iteration-level scheduling (the vLLM idea, built TPU-first): requests
join and leave the decode batch at token granularity instead of
decode-to-completion batches. Supersedes the coalescing batch queue
for LLM serving (ref: python/ray/serve/batching.py:46,215 — which can
only batch whole calls; a long completion there blocks every rider).

TPU/XLA design:
- ONE jitted decode step, compiled once, processes a fixed set of
  ``max_slots`` decode slots every iteration (static shapes). Inactive
  slots point at the null page (page 0) and their outputs are ignored
  host-side — no lax.cond, no divergence, no retrace.
- KV lives in a paged pool (models/kv_cache.py): the host-side
  BlockAllocator hands pages to sequences as they grow; completion or
  preemption returns them. Memory is bounded by the pool, not by
  max_slots x max_len.
- Decode is DEVICE-PACED: per-slot next-token and write position live
  on device and chain dispatch-to-dispatch; admission seeds slot rows
  with an on-stream scatter; token readbacks trail asynchronously and
  only ever block on a dispatch older than the newest one. With a
  full batch the scheduler runs ahead to the next completion event
  (dispatch-time arithmetic when no eos is configured), so the host
  syncs exactly when a scheduling decision is possible — host round
  trips (~84ms through a tunneled device) never gate the token rate.
  Join/leave granularity under load is ``chunk`` tokens.
- Preemption is recompute-based: when the pool runs dry the youngest
  slot is evicted, its pages freed, and the request requeued with
  prompt = original prompt + tokens generated so far, so clients see
  an uninterrupted stream.
- Pool pages are DONATED to each jitted call, so XLA updates them in
  place — decode does not copy the cache every step.

Works for every Llama-shaped family (Llama, Mixtral) since they share
LlamaAttention via block_forward.
"""
from __future__ import annotations

import collections
import dataclasses
import itertools
import queue
import threading
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.kv_cache import (BlockAllocator, PagedKVLayer,
                                     init_kv_pool)

_DONE = object()


def _dev_ready(buf) -> bool:
    """True when a device array's computation has finished (readback
    would not block). Conservative False when the runtime can't say."""
    try:
        return bool(buf.is_ready())
    except Exception:
        return False


class RequestError(Exception):
    pass


@dataclasses.dataclass
class _Request:
    rid: int
    prompt: List[int]            # original prompt (never mutated)
    max_new_tokens: int
    out_q: "queue.Queue[Any]" = dataclasses.field(
        default_factory=queue.Queue)
    generated: List[int] = dataclasses.field(default_factory=list)
    preemptions: int = 0
    error: Optional[BaseException] = None
    closed: bool = False         # _DONE delivered; drop late tokens

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.generated)

    @property
    def recompute_prompt(self) -> List[int]:
        """What to prefill after a preemption: everything the client
        has already seen."""
        return self.prompt + self.generated


class RequestHandle:
    """Client-side view of a submitted request."""

    def __init__(self, req: _Request):
        self._req = req

    def stream(self):
        """Yield generated token ids as they are produced."""
        while True:
            item = self._req.out_q.get()
            if item is _DONE:
                if self._req.error is not None:
                    raise self._req.error
                return
            yield item

    def result(self) -> List[int]:
        """Block until completion; return all generated token ids."""
        for _ in self.stream():
            pass
        return list(self._req.generated)


@dataclasses.dataclass
class _Slot:
    req: _Request
    pages: List[int]             # physical page ids, logical order
    pos: int                     # next KV write position (host mirror;
                                 # the device carries the live value)
    cur: Optional[int]           # None until the slot's seed scatter
                                 # is dispatched; afterwards a sentinel
                                 # — the next-token input lives ON
                                 # DEVICE (dev_cur), never read back
                                 # for dispatching
    admit_seq: int               # LIFO preemption order
    decoded: int = 0             # decode steps ridden (dispatch-time
                                 # arithmetic, ahead of emission)
    preempted: bool = False     # in-flight tokens must be discarded


class LLMEngine:
    """Continuous-batching decode engine for one model replica.

    Parameters
    ----------
    model, params: a Llama-family flax module + params.
    max_slots: decode batch width (static; compile-time).
    page_size: tokens per KV page.
    n_pages: physical pages in the pool (page 0 reserved as null).
    chunk: decode steps per device dispatch (host-sync amortization).
    """

    def __init__(self, model, params, *, max_slots: int = 8,
                 page_size: int = 16, n_pages: int = 256,
                 chunk: int = 4, temperature: float = 0.0,
                 eos_id: Optional[int] = None, seed: int = 0,
                 max_prefill_compiles: int = 16):
        self.model = model
        self.cfg = model.config
        self.params = params
        self.S = max_slots
        self.Pg = page_size
        self.K = chunk
        self.temperature = temperature
        self.eos_id = eos_id
        # Run-ahead ceiling: one dispatch may decode up to this many
        # steps before a host sync (the token buffer is [KMAX, S]).
        self.KMAX = max(chunk, 128)
        # Page-table width == the attention gather window (L =
        # max_pages * page_size per slot), so cap it at what the model
        # can legally address rather than the whole pool.
        self.max_pages = min(n_pages - 1,
                             -(-self.cfg.max_seq_len // page_size))
        self.alloc = BlockAllocator(n_pages)
        self.pages = init_kv_pool(self.cfg, n_pages, page_size)
        self.slots: List[Optional[_Slot]] = [None] * max_slots
        self._wait: "collections.deque[_Request]" = collections.deque()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._rid = itertools.count()
        self._admit_seq = itertools.count()
        self._rng = jax.random.PRNGKey(seed)
        # trailing readbacks: [(buf_dev, [(ix, slot, take), ...], steps)]
        self._fetchq: "collections.deque" = collections.deque()
        # in-flight prefills: [(firsts_dev, [(ix, slot, row), ...])]
        self._pending_prefill: List = []
        # Device-authoritative decode state: the next-token input and
        # write position per slot LIVE ON DEVICE and chain dispatch to
        # dispatch — no host readback sits on the decode critical
        # path. Admission seeds rows via a jitted scatter (no sync);
        # host readbacks trail for emission only.
        self._dev_cur = jnp.zeros((max_slots,), jnp.int32)
        self._dev_pos = jnp.zeros((max_slots,), jnp.int32)
        # Without an eos the schedule is fully deterministic: slots
        # retire by arithmetic at dispatch time and host syncs never
        # gate scheduling. With an eos, completions depend on sampled
        # tokens, so each iteration drains readbacks before planning.
        self._deferred = eos_id is None
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self.stats: Dict[str, int] = collections.Counter()
        self._prefill_cache: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._max_prefill_compiles = max_prefill_compiles
        # same-length waiting requests prefill together (one jitted
        # call, bucketed batch) up to this width
        self._max_prefill_batch = 4
        self._decode_fn = self._build_decode()
        self._seed_fn = self._build_seed()

    # ---------------------------------------------------------- public

    def submit(self, prompt_ids: List[int],
               max_new_tokens: int = 64) -> RequestHandle:
        prompt_ids = [int(t) for t in prompt_ids]
        if not prompt_ids:
            raise RequestError("empty prompt")
        if max_new_tokens < 1:
            raise RequestError("max_new_tokens must be >= 1")
        total = len(prompt_ids) + max_new_tokens
        need = -(-total // self.Pg)
        if need > self.alloc.n_pages - 1:
            raise RequestError(
                f"request needs {need} pages but pool has only "
                f"{self.alloc.n_pages - 1} usable pages")
        if total > self.cfg.max_seq_len:
            raise RequestError(
                f"prompt+completion {total} exceeds model "
                f"max_seq_len {self.cfg.max_seq_len}")
        req = _Request(next(self._rid), prompt_ids, max_new_tokens)
        with self._work:
            if self._stopped:
                raise RequestError("engine stopped")
            self._wait.append(req)
            self.stats["submitted"] += 1
            self._work.notify()
        return RequestHandle(req)

    def start(self) -> "LLMEngine":
        """Run the scheduler loop in a daemon thread."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="llm-engine", daemon=True)
            self._thread.start()
        return self

    def shutdown(self):
        with self._work:
            self._stopped = True
            for req in self._wait:
                req.error = RequestError("engine stopped")
                req.out_q.put(_DONE)
            self._wait.clear()
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)

    def step(self) -> bool:
        """One scheduler iteration, DEVICE-PACED:

            admit -> grow/preempt -> dispatch chunk k+1
                  -> fetch chunk k's tokens (trailing)

        Dispatch k+1 has NO data dependency on k's readback: the
        next-token input and write positions chain on device
        (dev_cur/dev_pos), admission seeds slot rows with a jitted
        scatter, and — with no eos configured — completions are
        dispatch-time arithmetic. The readback of chunk k then
        overlaps chunk k+1's compute, so neither the device round
        trip nor a slow host thread gates the token rate. With an
        eos, sampled tokens decide completion, so the iteration
        drains readbacks before planning (latency profile of the
        classic chunked loop). Returns False when idle."""
        with self._lock:
            if not self._deferred:
                self._drain_fetches_locked()   # emissions gate planning
            else:
                # Opportunistic: read back anything already finished
                # BEFORE admitting — free on a fast local device, and
                # it gets completions to clients (whose resubmissions
                # can then land during the upcoming dispatch) a full
                # dispatch earlier. Never blocks.
                self._drain_fetches_locked(ready_only=True)
            self._admit_locked()
            if not any(self.slots):
                if self._fetchq or self._pending_prefill:
                    self._drain_fetches_locked(limit=1)
                    return True
                return False
            steps = self._plan_steps_locked()
            if steps:
                self._grow_or_preempt_locked(steps)
                self._dispatch_chunk_locked(steps)
                if self._deferred:
                    self._retire_planned_locked()
            # trailing readback: block only on a dispatch OLDER than
            # the one just queued (keep=1), so the fetch round trip
            # overlaps the newest dispatch's compute — never its own
            self._drain_fetches_locked(limit=1, keep=1)
            return True

    def _plan_steps_locked(self) -> int:
        """How many decode steps the next dispatch should run.

        The host knows every slot's remaining budget, so when the
        batch is FULL it runs ahead on-device to the next completion
        event (min remaining over riders) — the only moment a
        scheduling decision is possible — instead of syncing every
        ``chunk`` steps. With a free slot, stick to ``chunk``-step
        dispatches so arrivals are admitted promptly. Never sync more
        often than ``chunk`` (a nearly-done slot rides a full window;
        its surplus steps land in the null page and are discarded).
        With an eos_id, run-ahead is bounded: tokens past an
        unpredicted EOS are wasted work."""
        rem = [self._owed(s) for s in self.slots
               if s is not None and s.cur is not None]
        if not rem:
            return 0         # all occupied slots await their seed
        # an unseeded slot joins at the next sync — treat it like a
        # free slot and keep the quick cadence
        free = any(s is None or s.cur is None for s in self.slots)
        if free:
            steps = self.K
        else:
            steps = max(self.K, min(rem))
        if self.eos_id is not None:
            steps = min(steps, 2 * self.K)
        return max(1, min(steps, self.KMAX))

    def _owed(self, slot: _Slot) -> int:
        """Decode steps this slot still needs, by dispatch-time
        arithmetic: the prefill emits token 1 of max_new_tokens, every
        ridden step emits one more. Runs AHEAD of emission (which
        trails with the readbacks) — with an eos the true need may be
        less; emission then closes the request early."""
        return slot.req.max_new_tokens - 1 - slot.decoded

    def _retire_planned_locked(self):
        """No-eos mode: free slots whose budget the dispatch just
        consumed — their tokens are still in flight (emission trails)
        but the SCHEDULE is deterministic, so the pages and the slot
        go back to the pool without waiting for a readback."""
        for i, slot in enumerate(self.slots):
            if (slot is not None and slot.cur is not None
                    and self._owed(slot) <= 0):
                self.slots[i] = None
                self.alloc.free(slot.pages)
                # "completed" counts at request close (emission)

    # ------------------------------------------------------- scheduler

    def _loop(self):
        while True:
            with self._work:
                while (not self._stopped and not self._wait
                       and not any(self.slots)
                       and not self._fetchq
                       and not self._pending_prefill):
                    self._work.wait()
                if self._stopped and not any(self.slots):
                    # deliver every token already computed before
                    # exiting — retired slots' readbacks still trail
                    self._drain_fetches_locked()
                    return
            try:
                self.step()
            except BaseException as e:   # fail every in-flight request
                self._fail_all(e)
                return

    def _fail_all(self, e: BaseException):
        with self._lock:
            failed = set()

            def fail(req):
                if req.closed or id(req) in failed:
                    return
                failed.add(id(req))
                req.error = e
                req.out_q.put(_DONE)

            for i, slot in enumerate(self.slots):
                if slot is not None:
                    fail(slot.req)
                    self.slots[i] = None
            # retired-at-dispatch requests whose tokens were still in
            # flight live only in the readback queues
            for _buf, riders, _steps in self._fetchq:
                for _i, slot, _t in riders:
                    fail(slot.req)
            for _f, placements in self._pending_prefill:
                for _ix, slot, _row in placements:
                    fail(slot.req)
            self._fetchq.clear()
            self._pending_prefill.clear()
            for req in self._wait:
                fail(req)
            self._wait.clear()
            self._stopped = True

    def _admit_locked(self):
        while self._wait:
            free = [i for i, s in enumerate(self.slots) if s is None]
            if not free:
                return
            # Batched prefill: take the FIFO PREFIX of the wait queue
            # sharing the head request's padded length (fixed-shape
            # serving traffic batches fully; mixed lengths degrade to
            # batch 1 — never reordering past a different-length
            # request keeps admission fair).
            head_pad = -(-max(1, len(self._wait[0].recompute_prompt))
                         // self.Pg) * self.Pg
            group = []
            for req in self._wait:
                if len(group) >= min(len(free), self._max_prefill_batch):
                    break
                prompt = req.recompute_prompt
                pad = -(-max(1, len(prompt)) // self.Pg) * self.Pg
                if pad != head_pad:
                    break
                n0 = max(1, -(-len(prompt) // self.Pg))
                page_ids = self.alloc.alloc(n0)
                if page_ids is None:
                    break      # pool dry: wait for completions
                group.append((req, prompt, page_ids))
            if not group:
                return
            for _ in group:
                self._wait.popleft()
            try:
                firsts = self._prefill_batch(
                    [(p, pids) for _, p, pids in group], head_pad)
            except BaseException as e:
                for req, _p, pids in group:
                    self.alloc.free(pids)
                    req.error = e
                    req.out_q.put(_DONE)
                continue
            placements = []
            for row, ((req, prompt, page_ids), ix) in enumerate(
                    zip(group, free)):
                slot = _Slot(req=req, pages=page_ids,
                             pos=len(prompt), cur=None,
                             admit_seq=next(self._admit_seq))
                self.slots[ix] = slot
                self.stats["admitted"] += 1
                placements.append((ix, slot, row))
            # Seed the device decode state from the prefill output
            # WITHOUT a host sync: scatter firsts/positions into
            # dev_cur/dev_pos rows on-stream, after which the slots
            # ride the very next dispatch.
            B = self._max_prefill_batch
            ixs = np.full((B,), self.S, np.int32)   # S = dropped row
            rows = np.zeros((B,), np.int32)
            posv = np.zeros((B,), np.int32)
            for r, (ix, slot, row) in enumerate(placements):
                ixs[r], rows[r], posv[r] = ix, row, slot.pos
            self._dev_cur, self._dev_pos = self._seed_fn(
                self._dev_cur, self._dev_pos, firsts,
                jnp.asarray(ixs), jnp.asarray(rows), jnp.asarray(posv))
            for ix, slot, _row in placements:
                slot.cur = -1      # device-seeded: ridable
            # firsts also stays on device for EMISSION: its readback
            # rides the next trailing sync, so admission never stalls
            # the decode stream on a host RTT
            self._pending_prefill.append((firsts, placements))

    def _grow_or_preempt_locked(self, steps: int):
        """Ensure every active slot's pages cover this dispatch's
        writes; evict the youngest slots if the pool runs dry."""
        for i in sorted(
                (i for i, s in enumerate(self.slots) if s is not None),
                key=lambda i: self.slots[i].admit_seq):
            slot = self.slots[i]
            if slot is None:        # evicted by an elder slot's growth
                continue
            if slot.cur is None:
                continue        # not riding this dispatch (seed not
                                # yet scattered): writes nothing
            eff = min(steps, max(1, self._owed(slot)))
            need = -(-(slot.pos + eff) // self.Pg)
            while len(slot.pages) < need:
                if self.slots[i] is not slot:
                    # a preemption's drain closed THIS slot (eos /
                    # budget in a trailing readback); growing the
                    # detached object would leak its new pages
                    break
                got = self.alloc.alloc(need - len(slot.pages))
                if got is not None:
                    slot.pages.extend(got)
                    break
                victim = max(
                    (j for j, s in enumerate(self.slots)
                     if s is not None and j != i),
                    key=lambda j: self.slots[j].admit_seq,
                    default=None)
                if victim is None:
                    # alone and still can't grow: submit() guarantees a
                    # lone request fits, so this is a logic error
                    raise RuntimeError("page pool exhausted by one slot")
                self._preempt_locked(victim)

    def _preempt_locked(self, ix: int):
        # The victim's generated-so-far must be complete before the
        # recompute prompt is frozen: drain every trailing readback
        # (rare path — preemption already pays a full re-prefill).
        victim = self.slots[ix]
        self._drain_fetches_locked()
        if self.slots[ix] is not victim:
            # the drain closed the victim (eos / budget in a trailing
            # readback): its pages are already freed — nothing to evict
            return
        slot = victim
        self.slots[ix] = None
        slot.preempted = True     # in-flight rows are recomputed
        self.alloc.free(slot.pages)
        slot.req.preemptions += 1
        self.stats["preemptions"] += 1
        self._wait.appendleft(slot.req)   # front: re-admit first

    def _dispatch_chunk_locked(self, steps: int):
        """Launch one decode dispatch of ``steps`` steps
        asynchronously. The full carry — pages, per-slot write
        position, per-slot next-token — lives on device and chains
        into the next dispatch; the host ships only the page table.
        The token buffer joins the trailing readback queue. ``steps``
        is a runtime scalar to the jitted fori_loop — no recompile
        per value."""
        pt = np.zeros((self.S, self.max_pages), np.int32)
        riders = []
        for i, slot in enumerate(self.slots):
            if slot is None or slot.cur is None:
                continue
            pt[i, :len(slot.pages)] = slot.pages
            # tokens this slot still owes its client from THIS
            # dispatch (the tail of an overshooting window is junk)
            take = min(steps, max(0, self._owed(slot)))
            riders.append((i, slot, take))
        (toks, self.pages, self._rng, self._dev_pos,
         self._dev_cur) = self._decode_fn(
            self.params, self.pages, jnp.asarray(pt),
            self._dev_pos, self._dev_cur, self._rng,
            jnp.int32(steps))
        # host mirrors advance NOW; emission trails
        for _i, slot, _t in riders:
            slot.pos += steps
            slot.decoded += steps
        self._fetchq.append((toks, riders, steps))
        self.stats["chunks"] += 1
        self.stats["decode_steps"] += steps

    def _drain_fetches_locked(self, limit: Optional[int] = None,
                              keep: int = 0,
                              ready_only: bool = False):
        """Trailing token readback: fetch up to ``limit`` outstanding
        decode buffers (None = all) plus EVERY in-flight prefill's
        firsts in one host sync each round, and emit to clients.
        Blocking here never stalls the device — the next dispatch is
        already queued behind the one being read."""
        blocking_rounds = 0
        while self._fetchq or self._pending_prefill:
            front_ready = bool(self._fetchq) and \
                _dev_ready(self._fetchq[0][0])
            # A finished buffer is always read (free — no block): on a
            # local device the previous dispatch is usually done by
            # now, so emission stays prompt. The `keep` fence only
            # protects STILL-COMPUTING dispatches — blocking on the
            # one just queued would serialize fetch after compute.
            take_buf = bool(self._fetchq) and (
                front_ready or
                (not ready_only and len(self._fetchq) > keep))
            # Prefill firsts ride along unless this is a ready-only
            # sweep and any of them is still computing (a sweep must
            # never block). Ordering stays safe: a rider's prefill is
            # always older than its first decode buffer, so a READY
            # front implies its riders' firsts are ready too — only
            # NEWER prefills (whose slots ride no fetched buffer yet)
            # can be withheld.
            pre_ready = bool(self._pending_prefill) and (
                not ready_only or all(
                    _dev_ready(f) for f, _ in self._pending_prefill))
            if not take_buf and not pre_ready:
                return
            if take_buf and not front_ready:
                if limit is not None and blocking_rounds >= limit:
                    return
                blocking_rounds += 1
            batch = []
            if take_buf:
                batch.append(self._fetchq.popleft())
            pend_pre = []
            if pre_ready:
                pend_pre, self._pending_prefill = \
                    self._pending_prefill, []
            vals = jax.device_get(
                [b[0] for b in batch] + [f for f, _ in pend_pre])
            k = len(batch)
            # prefill firsts FIRST: a slot's seeding prefill always
            # precedes its first decode ride, and both can land in
            # the same drain round
            for (_f, placements), firsts in zip(pend_pre, vals[k:]):
                for ix, slot, row in placements:
                    if slot.preempted:
                        continue
                    self._emit_to(slot.req, [int(firsts[row])], ix)
            for (_buf, riders, _steps), toks in zip(batch, vals):
                for i, slot, take in riders:
                    if slot.preempted:
                        continue    # recomputed from scratch
                    self._emit_to(slot.req, toks[:take, i].tolist(), i)

    def _emit_to(self, req: _Request, tokens: List[int], ix: int):
        """Deliver tokens to the request; close it when it hits eos
        or its budget. In no-eos mode the slot/pages were already
        retired at dispatch time; with an eos, closing here frees
        them (the readback is what reveals the eos)."""
        if req.closed:
            return
        done = False
        for t in tokens:
            t = int(t)
            req.generated.append(t)
            req.out_q.put(t)
            if ((self.eos_id is not None and t == self.eos_id)
                    or req.remaining <= 0):
                done = True
                break
        if done:
            req.closed = True
            slot = self.slots[ix]
            if slot is not None and slot.req is req:
                self.slots[ix] = None
                self.alloc.free(slot.pages)
            self.stats["completed"] += 1
            req.out_q.put(_DONE)

    # ----------------------------------------------------- jitted fns

    def _prefill_batch(self, items, T0pad: int) -> List[int]:
        """Prefill up to _max_prefill_batch same-padded-length prompts
        in ONE jitted call (bucketed batch: pad rows with dummies that
        scatter into the null page). items: [(prompt, page_ids), ...]"""
        n = len(items)
        # FIXED batch width: one executable per prompt length (dummy
        # rows scatter into the null page). Bucketed widths would
        # compile B=1/2/4 variants lazily — measured as multi-second
        # p99 stalls mid-load; a few dummy prefill rows are far
        # cheaper than a retrace.
        B = self._max_prefill_batch
        n_pages = T0pad // self.Pg
        fn = self._prefill_cache.get((T0pad, B))
        if fn is None:
            fn = self._build_prefill(T0pad, B)
            self._prefill_cache[(T0pad, B)] = fn
            while len(self._prefill_cache) > self._max_prefill_compiles:
                self._prefill_cache.popitem(last=False)
        self._prefill_cache.move_to_end((T0pad, B))
        ids = np.zeros((B, T0pad), np.int32)
        lens = np.ones((B,), np.int32)
        pids = np.zeros((B, n_pages), np.int32)   # dummies -> null page
        for r, (prompt, page_ids) in enumerate(items):
            ids[r, :len(prompt)] = prompt
            lens[r] = len(prompt)
            pids[r, :len(page_ids)] = page_ids
        firsts, self.pages, self._rng = fn(
            self.params, jnp.asarray(ids), jnp.asarray(lens),
            self.pages, jnp.asarray(pids), self._rng)
        self.stats["prefills"] += 1
        self.stats["prefilled_seqs"] += n
        # device array: the caller reads rows back at the next sync
        return firsts

    def _build_prefill(self, T0pad: int, B: int):
        model, cfg, Pg, temp = (self.model, self.cfg, self.Pg,
                                self.temperature)
        n_prompt_pages = T0pad // Pg
        from ray_tpu.models.llama import _pick_token, init_kv_caches

        def prefill(params, ids, true_lens, pages, page_ids, rng):
            rng, sub = jax.random.split(rng)
            caches = init_kv_caches(cfg, B, T0pad)
            logits, caches = model.apply(params, ids,
                                         kv_caches=caches, cache_len=0)
            flat_ids = page_ids.reshape(-1)     # [B * n_prompt_pages]
            new_pages = []
            for (pk, pv), (ck, cv) in zip(pages, caches):
                # dense cache [B, T0pad, KH, D] -> head-major pages
                # [KH, B*npp, Pg, D] scattered at [:, flat_ids]
                kp = ck.reshape(B * n_prompt_pages, Pg,
                                cfg.n_kv_heads, cfg.head_dim
                                ).transpose(2, 0, 1, 3)
                vp = cv.reshape(B * n_prompt_pages, Pg,
                                cfg.n_kv_heads, cfg.head_dim
                                ).transpose(2, 0, 1, 3)
                new_pages.append((
                    pk.at[:, flat_ids].set(kp.astype(pk.dtype)),
                    pv.at[:, flat_ids].set(vp.astype(pv.dtype))))
            last = logits[jnp.arange(B), true_lens - 1]    # [B, V]
            firsts = _pick_token(last, sub, temp)
            return firsts, new_pages, rng

        return jax.jit(prefill, donate_argnums=(3,))

    def _build_decode(self):
        model, temp = self.model, self.temperature
        KMAX, S = self.KMAX, self.S
        from ray_tpu.models.llama import _pick_token

        def decode(params, pages, page_table, pos, cur, rng, steps):
            # fori_loop with a RUNTIME bound: one executable serves
            # every dispatch length (chunk-sized quick syncs and full
            # run-ahead alike); tokens land in a fixed [KMAX, S]
            # buffer, rows past `steps` stay zero and are never read.
            # pos/cur are the DEVICE-authoritative per-slot state:
            # they chain dispatch-to-dispatch (admission seeds rows
            # via _build_seed's scatter), so no host readback ever
            # sits between two dispatches.
            buf0 = jnp.zeros((KMAX, S), jnp.int32)

            def body(i, carry):
                pages, pos, cur, key, buf = carry
                key, sub = jax.random.split(key)
                kv = [PagedKVLayer(pk, pv, page_table)
                      for pk, pv in pages]
                logits, new_kv = model.apply(
                    params, cur[:, None], kv_caches=kv, cache_len=pos)
                nxt = _pick_token(logits[:, -1], sub, temp)
                new_pages = [(c.pages_k, c.pages_v) for c in new_kv]
                return (new_pages, pos + 1, nxt, key, buf.at[i].set(nxt))
            pages, pos, cur, key, buf = jax.lax.fori_loop(
                0, steps, body, (pages, pos, cur, rng, buf0))
            # key/pos/cur return as device state: the host never syncs
            # on them between dispatches
            return buf, pages, key, pos, cur   # buf: [KMAX, S]

        return jax.jit(decode, donate_argnums=(1, 3, 4))

    def _build_seed(self):
        """Jitted admission seeding: scatter a prefill batch's first
        tokens and write positions into the device decode state.
        Rows padded with ix == S drop (mode='drop') — one executable
        regardless of how many slots the group filled."""
        def seed(dev_cur, dev_pos, firsts, ixs, rows, posv):
            return (dev_cur.at[ixs].set(firsts[rows], mode="drop"),
                    dev_pos.at[ixs].set(posv, mode="drop"))
        return jax.jit(seed, donate_argnums=(0, 1))
