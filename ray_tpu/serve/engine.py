"""Continuous-batching LLM engine with a paged KV cache.

Iteration-level scheduling (the vLLM idea, built TPU-first): requests
join and leave the decode batch at token granularity instead of
decode-to-completion batches. Supersedes the coalescing batch queue
for LLM serving (ref: python/ray/serve/batching.py:46,215 — which can
only batch whole calls; a long completion there blocks every rider).

TPU/XLA design:
- ONE jitted decode step, compiled once, processes a fixed set of
  ``max_slots`` decode slots every iteration (static shapes). Inactive
  slots point at the null page (page 0) and their outputs are ignored
  host-side — no lax.cond, no divergence, no retrace.
- KV lives in a paged pool (models/kv_cache.py): the host-side
  BlockAllocator hands pages to sequences as they grow; completion or
  preemption returns them. Memory is bounded by the pool, not by
  max_slots x max_len.
- Decode runs in chunks of ``chunk`` tokens per dispatch: one host
  sync per chunk amortizes the ~70ms tunneled-device readback latency
  (see generate_stream in models/llama.py) while keeping join/leave
  granularity at ``chunk`` tokens.
- Preemption is recompute-based: when the pool runs dry the youngest
  slot is evicted, its pages freed, and the request requeued with
  prompt = original prompt + tokens generated so far, so clients see
  an uninterrupted stream.
- Pool pages are DONATED to each jitted call, so XLA updates them in
  place — decode does not copy the cache every step.

Works for every Llama-shaped family (Llama, Mixtral) since they share
LlamaAttention via block_forward.
"""
from __future__ import annotations

import collections
import dataclasses
import itertools
import queue
import threading
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.kv_cache import (BlockAllocator, PagedKVLayer,
                                     init_kv_pool)

_DONE = object()


class RequestError(Exception):
    pass


@dataclasses.dataclass
class _Request:
    rid: int
    prompt: List[int]            # original prompt (never mutated)
    max_new_tokens: int
    out_q: "queue.Queue[Any]" = dataclasses.field(
        default_factory=queue.Queue)
    generated: List[int] = dataclasses.field(default_factory=list)
    preemptions: int = 0
    error: Optional[BaseException] = None

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.generated)

    @property
    def recompute_prompt(self) -> List[int]:
        """What to prefill after a preemption: everything the client
        has already seen."""
        return self.prompt + self.generated


class RequestHandle:
    """Client-side view of a submitted request."""

    def __init__(self, req: _Request):
        self._req = req

    def stream(self):
        """Yield generated token ids as they are produced."""
        while True:
            item = self._req.out_q.get()
            if item is _DONE:
                if self._req.error is not None:
                    raise self._req.error
                return
            yield item

    def result(self) -> List[int]:
        """Block until completion; return all generated token ids."""
        for _ in self.stream():
            pass
        return list(self._req.generated)


@dataclasses.dataclass
class _Slot:
    req: _Request
    pages: List[int]             # physical page ids, logical order
    pos: int                     # next KV write position
    cur: int                     # last sampled token (next step input)
    admit_seq: int               # LIFO preemption order


class LLMEngine:
    """Continuous-batching decode engine for one model replica.

    Parameters
    ----------
    model, params: a Llama-family flax module + params.
    max_slots: decode batch width (static; compile-time).
    page_size: tokens per KV page.
    n_pages: physical pages in the pool (page 0 reserved as null).
    chunk: decode steps per device dispatch (host-sync amortization).
    """

    def __init__(self, model, params, *, max_slots: int = 8,
                 page_size: int = 16, n_pages: int = 256,
                 chunk: int = 4, temperature: float = 0.0,
                 eos_id: Optional[int] = None, seed: int = 0,
                 max_prefill_compiles: int = 16):
        self.model = model
        self.cfg = model.config
        self.params = params
        self.S = max_slots
        self.Pg = page_size
        self.K = chunk
        self.temperature = temperature
        self.eos_id = eos_id
        # Page-table width == the attention gather window (L =
        # max_pages * page_size per slot), so cap it at what the model
        # can legally address rather than the whole pool.
        self.max_pages = min(n_pages - 1,
                             -(-self.cfg.max_seq_len // page_size))
        self.alloc = BlockAllocator(n_pages)
        self.pages = init_kv_pool(self.cfg, n_pages, page_size)
        self.slots: List[Optional[_Slot]] = [None] * max_slots
        self._wait: "collections.deque[_Request]" = collections.deque()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._rid = itertools.count()
        self._admit_seq = itertools.count()
        self._rng = jax.random.PRNGKey(seed)
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self.stats: Dict[str, int] = collections.Counter()
        self._prefill_cache: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._max_prefill_compiles = max_prefill_compiles
        self._decode_fn = self._build_decode()

    # ---------------------------------------------------------- public

    def submit(self, prompt_ids: List[int],
               max_new_tokens: int = 64) -> RequestHandle:
        prompt_ids = [int(t) for t in prompt_ids]
        if not prompt_ids:
            raise RequestError("empty prompt")
        if max_new_tokens < 1:
            raise RequestError("max_new_tokens must be >= 1")
        total = len(prompt_ids) + max_new_tokens
        need = -(-total // self.Pg)
        if need > self.alloc.n_pages - 1:
            raise RequestError(
                f"request needs {need} pages but pool has only "
                f"{self.alloc.n_pages - 1} usable pages")
        if total > self.cfg.max_seq_len:
            raise RequestError(
                f"prompt+completion {total} exceeds model "
                f"max_seq_len {self.cfg.max_seq_len}")
        req = _Request(next(self._rid), prompt_ids, max_new_tokens)
        with self._work:
            if self._stopped:
                raise RequestError("engine stopped")
            self._wait.append(req)
            self.stats["submitted"] += 1
            self._work.notify()
        return RequestHandle(req)

    def start(self) -> "LLMEngine":
        """Run the scheduler loop in a daemon thread."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="llm-engine", daemon=True)
            self._thread.start()
        return self

    def shutdown(self):
        with self._work:
            self._stopped = True
            for req in self._wait:
                req.error = RequestError("engine stopped")
                req.out_q.put(_DONE)
            self._wait.clear()
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)

    def step(self) -> bool:
        """One scheduler iteration: admit waiting requests, grow/
        preempt, decode one chunk. Returns False when idle. Use
        directly for deterministic tests; production uses start()."""
        with self._lock:
            self._admit_locked()
            if not any(self.slots):
                return False
            self._grow_or_preempt_locked()
            self._decode_chunk_locked()
            return True

    # ------------------------------------------------------- scheduler

    def _loop(self):
        while True:
            with self._work:
                while (not self._stopped and not self._wait
                       and not any(self.slots)):
                    self._work.wait()
                if self._stopped and not any(self.slots):
                    return
            try:
                self.step()
            except BaseException as e:   # fail every in-flight request
                self._fail_all(e)
                return

    def _fail_all(self, e: BaseException):
        with self._lock:
            for i, slot in enumerate(self.slots):
                if slot is not None:
                    slot.req.error = e
                    slot.req.out_q.put(_DONE)
                    self.slots[i] = None
            for req in self._wait:
                req.error = e
                req.out_q.put(_DONE)
            self._wait.clear()
            self._stopped = True

    def _admit_locked(self):
        while self._wait:
            free_ix = next((i for i, s in enumerate(self.slots)
                            if s is None), None)
            if free_ix is None:
                return
            req = self._wait[0]
            prompt = req.recompute_prompt
            n0 = max(1, -(-len(prompt) // self.Pg))
            page_ids = self.alloc.alloc(n0)
            if page_ids is None:
                return          # wait for completions to release pages
            self._wait.popleft()
            try:
                first = self._prefill(prompt, page_ids)
            except BaseException as e:
                self.alloc.free(page_ids)
                req.error = e
                req.out_q.put(_DONE)
                continue
            slot = _Slot(req=req, pages=page_ids, pos=len(prompt),
                         cur=first, admit_seq=next(self._admit_seq))
            self.slots[free_ix] = slot
            self.stats["admitted"] += 1
            self._emit(free_ix, [first])

    def _grow_or_preempt_locked(self):
        """Ensure every active slot's pages cover this chunk's writes;
        evict the youngest slots if the pool runs dry."""
        for i in sorted(
                (i for i, s in enumerate(self.slots) if s is not None),
                key=lambda i: self.slots[i].admit_seq):
            slot = self.slots[i]
            if slot is None:        # evicted by an elder slot's growth
                continue
            steps = min(self.K, slot.req.remaining)
            need = -(-(slot.pos + steps) // self.Pg)
            while len(slot.pages) < need:
                got = self.alloc.alloc(need - len(slot.pages))
                if got is not None:
                    slot.pages.extend(got)
                    break
                victim = max(
                    (j for j, s in enumerate(self.slots)
                     if s is not None and j != i),
                    key=lambda j: self.slots[j].admit_seq,
                    default=None)
                if victim is None:
                    # alone and still can't grow: submit() guarantees a
                    # lone request fits, so this is a logic error
                    raise RuntimeError("page pool exhausted by one slot")
                self._preempt_locked(victim)

    def _preempt_locked(self, ix: int):
        slot = self.slots[ix]
        self.slots[ix] = None
        self.alloc.free(slot.pages)
        slot.req.preemptions += 1
        self.stats["preemptions"] += 1
        self._wait.appendleft(slot.req)   # front: re-admit first

    def _decode_chunk_locked(self):
        pt = np.zeros((self.S, self.max_pages), np.int32)
        pos = np.zeros((self.S,), np.int32)
        cur = np.zeros((self.S,), np.int32)
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            pt[i, :len(slot.pages)] = slot.pages
            pos[i] = slot.pos
            cur[i] = slot.cur
        self._rng, sub = jax.random.split(self._rng)
        toks, self.pages = self._decode_fn(
            self.params, self.pages, jnp.asarray(pt),
            jnp.asarray(pos), jnp.asarray(cur), sub)
        toks = np.asarray(toks)               # ONE sync per chunk
        self.stats["chunks"] += 1
        self.stats["decode_steps"] += self.K
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            accept = toks[:min(self.K, slot.req.remaining), i].tolist()
            slot.pos += self.K
            slot.cur = accept[-1] if accept else slot.cur
            self._emit(i, accept)

    def _emit(self, ix: int, tokens: List[int]):
        """Deliver tokens to the request; close out the slot when the
        request hits eos or its budget."""
        slot = self.slots[ix]
        req = slot.req
        done = False
        for t in tokens:
            t = int(t)
            req.generated.append(t)
            req.out_q.put(t)
            if ((self.eos_id is not None and t == self.eos_id)
                    or req.remaining <= 0):
                done = True
                break
        if done:
            self.slots[ix] = None
            self.alloc.free(slot.pages)
            self.stats["completed"] += 1
            req.out_q.put(_DONE)

    # ----------------------------------------------------- jitted fns

    def _prefill(self, prompt: List[int], page_ids: List[int]) -> int:
        T0 = len(prompt)
        T0pad = -(-T0 // self.Pg) * self.Pg
        fn = self._prefill_cache.get(T0pad)
        if fn is None:
            fn = self._build_prefill(T0pad)
            self._prefill_cache[T0pad] = fn
            while len(self._prefill_cache) > self._max_prefill_compiles:
                self._prefill_cache.popitem(last=False)
        self._prefill_cache.move_to_end(T0pad)
        ids = np.zeros((1, T0pad), np.int32)
        ids[0, :T0] = prompt
        pids = np.asarray(page_ids, np.int32)
        self._rng, sub = jax.random.split(self._rng)
        first, self.pages = fn(self.params, jnp.asarray(ids),
                               jnp.int32(T0), self.pages,
                               jnp.asarray(pids), sub)
        self.stats["prefills"] += 1
        return int(first)

    def _build_prefill(self, T0pad: int):
        model, cfg, Pg, temp = (self.model, self.cfg, self.Pg,
                                self.temperature)
        n_prompt_pages = T0pad // Pg
        from ray_tpu.models.llama import _pick_token, init_kv_caches

        def prefill(params, ids, true_len, pages, page_ids, rng):
            caches = init_kv_caches(cfg, 1, T0pad)
            logits, caches = model.apply(params, ids,
                                         kv_caches=caches, cache_len=0)
            new_pages = []
            for (pk, pv), (ck, cv) in zip(pages, caches):
                kp = ck[0].reshape(n_prompt_pages, Pg,
                                   cfg.n_kv_heads, cfg.head_dim)
                vp = cv[0].reshape(n_prompt_pages, Pg,
                                   cfg.n_kv_heads, cfg.head_dim)
                new_pages.append((
                    pk.at[page_ids].set(kp.astype(pk.dtype)),
                    pv.at[page_ids].set(vp.astype(pv.dtype))))
            first = _pick_token(logits[0, true_len - 1][None], rng,
                                temp)[0]
            return first, new_pages

        return jax.jit(prefill, donate_argnums=(3,))

    def _build_decode(self):
        model, K, temp = self.model, self.K, self.temperature
        from ray_tpu.models.llama import _pick_token

        def decode(params, pages, page_table, pos, cur, rng):
            def body(carry, _):
                pages, pos, cur, key = carry
                key, sub = jax.random.split(key)
                kv = [PagedKVLayer(pk, pv, page_table)
                      for pk, pv in pages]
                logits, new_kv = model.apply(
                    params, cur[:, None], kv_caches=kv, cache_len=pos)
                nxt = _pick_token(logits[:, -1], sub, temp)
                new_pages = [(c.pages_k, c.pages_v) for c in new_kv]
                return (new_pages, pos + 1, nxt, key), nxt
            (pages, _, _, _), toks = jax.lax.scan(
                body, (pages, pos, cur, rng), None, length=K)
            return toks, pages        # toks: [K, S]

        return jax.jit(decode, donate_argnums=(1,))
