"""Model multiplexing: many models behind one deployment.

Capability parity with the reference's model multiplexing
(python/ray/serve/api.py @serve.multiplexed +
serve/_private/... ModelMultiplexWrapper; the LoRA-serving pattern):
a replica lazily loads models by id into a bounded per-replica LRU,
and the handle routes requests for a model id to a replica that
already holds it (cache affinity) so the fleet converges to a stable
model->replica assignment without central placement.

Usage:

    @serve.deployment(max_ongoing_requests=8)
    class Multi:
        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id: str):
            return load_expensive_model(model_id)

        def __call__(self, payload):
            model = self.get_model(serve.get_multiplexed_model_id())
            return model(payload)

    h = serve.run(Multi.bind())
    h.options(multiplexed_model_id="m1").remote(x)
"""
from __future__ import annotations

import collections
import contextvars
import functools
import threading
from typing import Callable, Optional

# The model id of the request being executed, set by the replica
# around the user method (context parity with
# serve.context._serve_request_context). A ContextVar so it follows
# the request across the replica's off-loop executor hop
# (copy_context in controller.handle_request).
_model_id_var: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "raytpu_mux_model_id", default="")

# Kwarg smuggling the model id through the request path; stripped by
# the replica before the user method sees kwargs.
MUX_KWARG = "__mux_model_id"


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id the current request asked for
    (empty string when the caller set none)."""
    return _model_id_var.get()


def _set_request_model_id(model_id: Optional[str]):
    _model_id_var.set(model_id or "")


def multiplexed(_fn: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorate the replica method that loads a model by id: results
    cache in a per-replica LRU of at most max_num_models_per_replica
    entries; eviction calls the old model's ``__del__`` (drop the
    reference) after calling an optional ``unload()`` hook."""

    def wrap(fn):
        cache_attr = f"__mux_cache_{fn.__name__}"
        lock_attr = f"__mux_lock_{fn.__name__}"

        loading_attr = f"__mux_loading_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(self, model_id: str):
            lock = getattr(self, lock_attr, None)
            if lock is None:
                lock = threading.Lock()
                setattr(self, lock_attr, lock)
            while True:
                with lock:
                    cache = getattr(self, cache_attr, None)
                    if cache is None:
                        cache = collections.OrderedDict()
                        setattr(self, cache_attr, cache)
                    if model_id in cache:
                        cache.move_to_end(model_id)
                        return cache[model_id]
                    loading = getattr(self, loading_attr, None)
                    if loading is None:
                        loading = {}
                        setattr(self, loading_attr, loading)
                    ev = loading.get(model_id)
                    if ev is None:
                        loading[model_id] = threading.Event()
                        break               # this caller loads
                # Another request is loading the same id: wait for it
                # instead of loading a duplicate (N concurrent loads =
                # N x load time + N models in memory, and the N-1
                # dropped copies would skip their unload() hook).
                ev.wait(timeout=600)
            # Load OUTSIDE the lock (loads are slow; concurrent
            # requests for cached models must not queue behind one).
            try:
                model = fn(self, model_id)
            except BaseException:
                with lock:
                    getattr(self, loading_attr).pop(model_id).set()
                raise
            with lock:
                cache[model_id] = model
                cache.move_to_end(model_id)
                while len(cache) > max_num_models_per_replica:
                    _mid, old = cache.popitem(last=False)
                    unload = getattr(old, "unload", None)
                    if callable(unload):
                        try:
                            unload()
                        except Exception:
                            pass
                getattr(self, loading_attr).pop(model_id).set()
            return model

        wrapper.__is_multiplexed__ = True
        wrapper.__max_models__ = max_num_models_per_replica
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
