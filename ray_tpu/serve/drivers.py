"""Ingress drivers: DAGDriver + HTTP adapters.

Capability parity with the reference's driver layer
(python/ray/serve/drivers.py DAGDriver — an ingress deployment that
executes a deployment graph per request and optionally adapts raw HTTP
payloads into model inputs via `http_adapter`, the pattern of
serve/http_adapters.py). Two ingress shapes:

- single graph: ``DAGDriver.bind(graph_node)`` — every request runs
  the bound graph (``predict``);
- route table: ``DAGDriver.bind({"/a": DepA.bind(), ...})`` — the
  path picks the sub-graph (``predict_with_route`` / ``__call__``).

Bound deployments inside the argument are deployed recursively by
serve.run and arrive here as live handles.
"""
from __future__ import annotations

import json
from typing import Any, Callable, Dict, Optional, Union

import ray_tpu


# --------------------------------------------------------------------------
# HTTP adapters (reference: python/ray/serve/http_adapters.py)
# --------------------------------------------------------------------------

def json_request(body: Union[bytes, str, Dict]) -> Any:
    """Default adapter: parse a JSON body into the model input."""
    if isinstance(body, (bytes, bytearray)):
        body = body.decode()
    if isinstance(body, str):
        return json.loads(body) if body else None
    return body


def json_to_ndarray(body: Union[bytes, str, Dict]) -> Any:
    """Adapter for numeric payloads: {"array": [...]} -> np.ndarray
    (reference: http_adapters.json_to_ndarray)."""
    import numpy as np
    data = json_request(body)
    if isinstance(data, dict) and "array" in data:
        return np.asarray(data["array"])
    return np.asarray(data)


def starlette_request(body: Any) -> Any:
    """Identity adapter: hand the raw request payload through."""
    return body


class DAGDriver:
    """Graph ingress (reference: serve/drivers.py:DAGDriver).

    The driver is itself a deployment; serve.run deploys the bound
    graph(s) beneath it and the HTTP proxy (serve.start_http) reaches
    it like any deployment — POST /DAGDriver with a JSON body routes
    through ``__call__``.
    """

    def __init__(self, dags: Union[Any, Dict[str, Any]],
                 http_adapter: Optional[Callable] = None):
        self._adapter = http_adapter or json_request
        self._adapter_explicit = http_adapter is not None
        if isinstance(dags, dict):
            self._routes: Dict[str, Any] = dict(dags)
            self._entry = None
        else:
            self._routes = {}
            self._entry = dags

    # -- introspection -----------------------------------------------------

    def routes(self) -> Dict[str, str]:
        out = {path: getattr(h, "_name", repr(h))
               for path, h in self._routes.items()}
        if self._entry is not None:
            out["/"] = getattr(self._entry, "_name", repr(self._entry))
        return out

    # -- request paths -----------------------------------------------------

    def _resolve(self, handle, *args, **kwargs):
        out = handle.remote(*args, **kwargs)
        # Deployment handles return ObjectRefs; DAG nodes may return
        # nested refs — resolve to the final value for the caller.
        from ray_tpu._private.object_ref import ObjectRef
        while isinstance(out, ObjectRef):
            out = ray_tpu.get(out)
        return out

    def predict(self, *args, **kwargs):
        """Run the single bound graph (reference: dag_handle.predict)."""
        if self._entry is None:
            raise ValueError(
                "DAGDriver was bound with a route table; use "
                "predict_with_route(path, ...) or __call__(path, ...)")
        return self._resolve(self._entry, *args, **kwargs)

    def predict_with_route(self, route_path: str, *args, **kwargs):
        h = self._routes.get(route_path)
        if h is None:
            raise KeyError(
                f"No route {route_path!r}; known: "
                f"{sorted(self._routes)}")
        if self._adapter_explicit and len(args) == 1 and not kwargs:
            # An explicitly-configured adapter applies to route-table
            # requests too (single-payload form, the HTTP shape).
            args = (self._adapter(args[0]),)
        return self._resolve(h, *args, **kwargs)

    def __call__(self, request: Any = None, *args, **kwargs):
        """HTTP-shaped entry: for a route-table driver the first
        argument is the path; for a single-graph driver the request
        body goes through the http_adapter and into the graph."""
        if self._entry is None:
            if args or kwargs:
                return self.predict_with_route(request, *args,
                                               **kwargs)
            # Path-only call (health checks / route probing).
            return self.predict_with_route(request)
        if args or kwargs:
            raise TypeError(
                "single-graph DAGDriver takes exactly one request "
                f"payload; got extra args={args!r} kwargs={kwargs!r}")
        payload = self._adapter(request) if request is not None \
            else None
        return self.predict(payload)
