"""Ingress drivers.

Capability parity with the reference's DAGDriver
(python/ray/serve/drivers.py — an ingress deployment routing HTTP paths
to the deployment graph's entry handles).
"""
from __future__ import annotations

from typing import Any, Dict

import ray_tpu


class DAGDriver:
    """Route-table ingress: maps path prefixes to deployment handles.

    Use: serve.run(serve.deployment(DAGDriver).bind(
             {"/a": DepA.bind(), "/b": DepB.bind()}))
    Bound deployments in the dict are deployed recursively by serve.run
    and arrive here as live handles.
    """

    def __init__(self, route_table: Dict[str, Any]):
        self._routes = dict(route_table)

    def routes(self) -> Dict[str, str]:
        return {path: getattr(h, "_name", repr(h))
                for path, h in self._routes.items()}

    def __call__(self, path: str, *args, **kwargs):
        h = self._routes.get(path)
        if h is None:
            raise KeyError(
                f"No route {path!r}; known: {sorted(self._routes)}")
        return ray_tpu.get(h.remote(*args, **kwargs))
