"""Model-free speculative decoding: prompt-lookup n-gram proposer.

Steady-state decode advances every slot exactly one token per model
dispatch — the per-token latency floor is one full forward pass.
Draft-and-verify decoding raises tokens-per-dispatch without a draft
model: the HOST proposes the next few tokens by looking the current
n-gram suffix up in the slot's own prompt+generated history (prompt
lookup / n-gram self-speculation — free on the repetitive suffixes
that dominate extraction, code-edit, and multi-turn-chat loads), and
the engine scores all ``spec_len + 1`` positions in ONE batched
forward pass through the existing paged multi-token branch
(ops/paged_attention.paged_append + the llama.py paged ``T>=1``
path). The longest draft prefix matching the greedy argmax is
accepted, plus the argmax token after it (the standard bonus token),
so a verify dispatch yields between 1 and ``spec_len + 1`` tokens.

Exactness: every emitted token IS a greedy argmax of the model's own
logits over the same KV the plain decode step would see — drafts only
decide how many of those argmaxes one dispatch gets to keep, never
what they are. At temperature 0 the accepted stream is therefore
token-identical to non-speculative decode (enforced by
tests/test_spec_decode.py). Rejected positions cost nothing to state:
the engine rolls back by clamping the slot's KV write offset — the
garbage KV beyond the new frontier is overwritten before any query
can attend to it, and the pages stay owned by the slot.

The proposer here is pure host-side bookkeeping (no jax): a rolling
index from every ``ngram``-token window to its most recent earlier
occurrence, extended incrementally as tokens emit. ``propose`` is
O(1) per call; ``sync`` is O(new tokens).

Metrics (util/metrics.py, Prometheus text via the dashboard):
proposed/accepted/rejected token counters plus a per-verify
accept-rate histogram.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

PROPOSED_TOKENS = "serve_spec_proposed_tokens"
ACCEPTED_TOKENS = "serve_spec_accepted_tokens"
REJECTED_TOKENS = "serve_spec_rejected_tokens"
ACCEPT_RATE = "serve_spec_accept_rate"

_METRICS: Optional[dict] = None


def metrics() -> dict:
    """Lazy module-level metric singletons, re-created if a test's
    ``clear_registry()`` dropped them (same discipline as
    serve/prefix_cache.py: registration is global per process, values
    live on the instances)."""
    global _METRICS
    from ray_tpu.util import metrics as m
    if (_METRICS is None
            or m.registry().get(PROPOSED_TOKENS)
            is not _METRICS["proposed"]):
        _METRICS = {
            "proposed": m.Counter(
                PROPOSED_TOKENS,
                "Draft tokens proposed to verify dispatches"),
            "accepted": m.Counter(
                ACCEPTED_TOKENS,
                "Draft tokens accepted (matched the greedy argmax)"),
            "rejected": m.Counter(
                REJECTED_TOKENS,
                "Draft tokens rejected (rolled back by clamping the "
                "slot's KV offset)"),
            "accept_rate": m.Histogram(
                ACCEPT_RATE,
                "Per-slot-per-verify draft accept rate",
                boundaries=[0.1, 0.25, 0.5, 0.75, 0.9, 1.0]),
        }
    return _METRICS


class NGramIndex:
    """Rolling n-gram index over one slot's prompt+generated tokens.

    Maps every ``n``-token window to the position just PAST its most
    recent occurrence, keeping one generation of history per gram so
    the current suffix (always the newest occurrence of itself) can
    still find its previous one. ``propose(k)`` returns the up-to-k
    tokens that followed the suffix's previous occurrence — the
    prompt-lookup draft.

    The engine keeps one per slot and calls ``sync`` with the full
    context each round; only the unseen tail is consumed, so a slot's
    index costs O(1) per generated token over its lifetime. Preemption
    discards the slot (and this index) wholesale; re-admission builds
    a fresh one from the recompute prompt — mid-flight state can never
    leak across an eviction.
    """

    def __init__(self, n: int = 3):
        if n < 1:
            raise ValueError("ngram order must be >= 1")
        self.n = n
        self._tokens: List[int] = []
        # gram -> index just past its latest occurrence, and the one
        # before that (the suffix gram's latest occurrence is itself)
        self._last: Dict[Tuple[int, ...], int] = {}
        self._prev: Dict[Tuple[int, ...], int] = {}

    def __len__(self) -> int:
        return len(self._tokens)

    def sync(self, context: Sequence[int]) -> None:
        """Extend the index with ``context``'s unseen tail. The caller
        always passes the slot's full prompt+generated stream; tokens
        already indexed are skipped, so this never re-scans.

        Stale-frontier contract (the engine's overlapped loop): the
        context may TRAIL the device frontier by up to one round's
        undrained decode steps, and consecutive syncs may pass the
        identical context (no drain landed between rounds — the tail
        is then empty and this is a no-op). What it may never do is
        SHRINK: ``prompt + generated`` is append-only for a live
        slot, and a preempted slot discards this index wholesale
        rather than rewinding it. Shrinkage means the engine fed a
        different request's stream into this slot's index — raise
        loudly."""
        if len(context) < len(self._tokens):
            raise ValueError(
                f"context shrank: indexed {len(self._tokens)} tokens "
                f"but got {len(context)}")
        for t in context[len(self._tokens):]:
            self._tokens.append(int(t))
            if len(self._tokens) >= self.n:
                gram = tuple(self._tokens[-self.n:])
                if gram in self._last:
                    self._prev[gram] = self._last[gram]
                self._last[gram] = len(self._tokens)
        return None

    def propose(self, k: int) -> List[int]:
        """Draft up to ``k`` tokens continuing the current suffix from
        its most recent earlier occurrence; [] when the suffix has
        never occurred before (or the context is shorter than the
        gram). Drafts are hints only — verification decides."""
        if k <= 0 or len(self._tokens) < self.n:
            return []
        tail = tuple(self._tokens[-self.n:])
        end = self._last.get(tail)
        if end == len(self._tokens):     # newest occurrence is us
            end = self._prev.get(tail)
        if end is None:
            return []
        return list(self._tokens[end:end + k])
