"""Offline batch-inference tier: streaming pipelines over the
online serving fleet, on the BATCH priority lane.

The serving engine and the data layer were strangers: ``LLMEngine``/
``EnginePool`` served interactive traffic, ``ray_tpu/data`` fed
training. This module is the bridge the runtime thesis calls for —
one fleet, heterogeneous workloads: a ``BatchInferenceJob`` drives
``ds.map_batches``-style sources (plain iterables, a ``Dataset``, or
a windowed ``DatasetPipeline``) through the SAME engines that serve
online traffic, as ``priority=LANE_BATCH`` requests.

The lane contract (scheduler.py / engine.py / engine_pool.py) is what
makes overnight colocation safe:

- a batch request admits only when no online request is waiting
  (per-lane FIFO, online lane always first);
- a batch slot is the FIRST preemption victim — for online admission,
  page pressure, anywhere a victim is hunted — and re-admits
  token-identically (recompute or prefix-cache resume);
- batch backlog is bounded by ``max_queued_batch`` and reported in
  its own ``queue_depth_batch`` lane, so routing saturation and the
  autoscaler never react to preemptible work;
- pool routing for the lane is pure spill — least batch backlog,
  never touching the sticky/affinity placement online traffic owns.

Progress is checkpointed with the air.checkpoint sha256-manifest
discipline (stage -> fsync -> manifest -> atomic rename): the driver
periodically commits a manifest of completed rows keyed by GLOBAL ROW
INDEX, so a job killed at any instant resumes exactly-once — a
completed-but-uncommitted row is recomputed (keyed overwrite, never a
duplicate), and a torn checkpoint directory is refused loudly by
``Checkpoint.from_directory`` rather than resumed wrong.

Knob preset: ``engine_kwargs_for_profile("throughput")`` maps the
scheduler's throughput profile onto ``LLMEngine`` constructor knobs —
deep no-TTFT-SLO queues, large prefill chunks, long decode run-ahead.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.serve.errors import DeadlineExceeded, RequestCancelled
from ray_tpu.serve.scheduler import LANE_BATCH, scheduler_profile


def engine_kwargs_for_profile(name: str) -> Dict[str, Any]:
    """Map a named scheduler profile ('latency' | 'throughput') onto
    ``LLMEngine`` constructor kwargs. The profile dict is pure data
    in the planner module (import-guarded); this is the layer that
    knows which engine knob each key lands on."""
    p = scheduler_profile(name)
    return {
        "chunk": p["decode_chunk"],
        "prefill_chunk": p["prefill_chunk"],
        "max_run_ahead": p["max_run_ahead"],
        "max_queued": p["max_queued"],
    }


class BatchRowError(RuntimeError):
    """A row exhausted its retry budget; carries the row index and
    the last underlying failure."""

    def __init__(self, index: int, cause: BaseException):
        super().__init__(
            f"batch row {index} failed after retries: {cause!r}")
        self.index = index
        self.cause = cause


class BatchInferenceJob:
    """Streaming batch-generation driver over one engine or pool.

    Parameters
    ----------
    target: anything with the engine ``submit`` surface
        (``LLMEngine`` or ``EnginePool``) — requests go in with
        ``priority=LANE_BATCH``. The target must be started/serving;
        the job never owns its lifecycle.
    source: the rows to generate for — a plain iterable of prompts
        (token-id lists), a ``Dataset``, or a ``DatasetPipeline``
        (windowed execution: one window of blocks is resident at a
        time). Iteration order MUST be deterministic across runs —
        row identity for exactly-once resume is the global iteration
        index.
    prompt_fn: row -> token-id list (default: the row IS the prompt).
    max_new_tokens: per-row generation budget.
    max_in_flight: the driver's concurrency window — how many rows
        are submitted but unharvested at once. This, not the engine
        queue bound, is the batch tier's depth knob (the throughput
        profile leaves ``max_queued_batch`` unbounded on purpose).
    checkpoint_dir: progress-manifest directory. None disables
        checkpointing (and resume).
    checkpoint_every: commit a manifest every N newly completed rows
        (and always once more at the end).
    max_row_retries: bounded per-row resubmits after engine faults.
        Cancels and deadline expiries are the caller's intent and
        never retried.
    pipeline_stats: pre-computed per-stage stats to embed in every
        manifest; Dataset/DatasetPipeline sources collect their own
        (``materialize(collect_stats=True)`` -> ``stats_dict()``)
        and append per window.
    """

    def __init__(self, target, source, *,
                 prompt_fn: Optional[Callable[[Any], List[int]]] = None,
                 max_new_tokens: int = 64,
                 max_in_flight: int = 64,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 64,
                 max_row_retries: int = 2,
                 job_id: str = "batch-job",
                 pipeline_stats: Optional[List[Dict[str, Any]]] = None):
        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        if checkpoint_every < 1:
            raise ValueError("checkpoint_every must be >= 1")
        self._target = target
        self._source = source
        self._prompt_fn = prompt_fn or (lambda row: row)
        self._mnt = int(max_new_tokens)
        self._window = int(max_in_flight)
        self._ckpt_dir = checkpoint_dir
        self._ckpt_every = int(checkpoint_every)
        self._max_row_retries = max(0, int(max_row_retries))
        self._job_id = job_id
        self._pipeline_stats: List[Dict[str, Any]] = list(
            pipeline_stats or [])
        # global row index -> generated token ids (the exactly-once
        # ledger: keyed overwrite makes recomputing an uncommitted
        # row idempotent)
        self._completed: Dict[int, List[int]] = {}
        self._resumed_rows = 0
        self.stats: Dict[str, Any] = {
            "rows_completed": 0, "rows_resumed": 0,
            "rows_retried": 0, "checkpoints_written": 0,
            "batch_tokens": 0,
        }

    # ----------------------------------------------------------- source

    def _iter_rows(self) -> Iterator[Any]:
        """Yield rows in deterministic order, collecting per-stage
        pipeline stats where the source supports it. Local imports:
        the tier must not couple serve to the data layer for plain
        iterable sources."""
        try:
            from ray_tpu.data.dataset import Dataset
            from ray_tpu.data.pipeline import DatasetPipeline
        except Exception:            # data layer absent/stubbed
            Dataset = DatasetPipeline = ()
        src = self._source
        if isinstance(src, DatasetPipeline):
            for window in src.iter_windows():
                yield from self._iter_dataset(window)
            return
        if isinstance(src, Dataset):
            yield from self._iter_dataset(src)
            return
        yield from src

    def _iter_dataset(self, ds) -> Iterator[Any]:
        executed = ds.materialize(collect_stats=True)
        for ref in executed._block_refs:
            import ray_tpu
            yield from ray_tpu.get(ref)
        sd = executed.stats_dict()
        if sd is not None:
            self._pipeline_stats.append(sd)

    # ----------------------------------------------------- checkpointing

    def _load_checkpoint(self) -> None:
        if self._ckpt_dir is None:
            return
        import os
        if not os.path.isdir(self._ckpt_dir) \
                or not os.listdir(self._ckpt_dir):
            # absent or empty: a fresh start, not torn state — the
            # manifest commit is a staged atomic rename, so a torn
            # commit never leaves the directory empty
            return
        # refuses torn state (InvalidCheckpointError) — resuming a
        # half-written ledger silently would break exactly-once
        data = Checkpoint.from_directory(self._ckpt_dir).to_dict()
        if data.get("job_id") != self._job_id:
            raise ValueError(
                f"checkpoint at {self._ckpt_dir} belongs to job "
                f"{data.get('job_id')!r}, not {self._job_id!r}")
        self._completed = {int(k): list(v)
                           for k, v in data.get("completed",
                                                {}).items()}
        self._resumed_rows = len(self._completed)
        self.stats["rows_resumed"] = self._resumed_rows

    def _write_checkpoint(self) -> None:
        if self._ckpt_dir is None:
            return
        Checkpoint.from_dict({
            "job_id": self._job_id,
            "completed": dict(self._completed),
            "pipeline_stats": list(self._pipeline_stats),
            "stats": dict(self.stats),
        }).to_directory(self._ckpt_dir,
                        step=len(self._completed))
        self.stats["checkpoints_written"] += 1

    # ------------------------------------------------------------ driving

    def _submit(self, prompt: List[int]):
        return self._target.submit(prompt, max_new_tokens=self._mnt,
                                   priority=LANE_BATCH)

    def run(self) -> List[List[int]]:
        """Drive the job to completion; returns the generated token
        ids for every row, in row order. Resumes from the checkpoint
        directory when one exists: committed rows are skipped
        outright (their results load from the manifest), uncommitted
        ones recompute — 0 duplicate / 0 missing rows by keyed-index
        construction."""
        self._load_checkpoint()
        # (index, prompt, retries_left, handle) — harvested oldest-
        # first. Head-of-line harvest order costs nothing: every
        # in-flight row is progressing concurrently inside the
        # engine regardless of the order results are collected.
        in_flight: deque = deque()
        since_ckpt = 0
        rows = self._iter_rows()
        n_total = 0
        exhausted = False
        while True:
            while not exhausted and len(in_flight) < self._window:
                try:
                    row = next(rows)
                except StopIteration:
                    exhausted = True
                    break
                idx = n_total
                n_total += 1
                if idx in self._completed:
                    continue       # resumed: committed in a prior run
                prompt = [int(t) for t in self._prompt_fn(row)]
                in_flight.append((idx, prompt,
                                  self._max_row_retries,
                                  self._submit(prompt)))
            if not in_flight:
                if exhausted:
                    break
                continue
            idx, prompt, retries, handle = in_flight.popleft()
            try:
                toks = handle.result()
            except (RequestCancelled, DeadlineExceeded):
                raise                # caller intent: never retried
            except Exception as e:   # shutdown/drain/fault: bounded
                                     # resubmit, same row index
                if retries <= 0:
                    raise BatchRowError(idx, e) from e
                self.stats["rows_retried"] += 1
                in_flight.append((idx, prompt, retries - 1,
                                  self._submit(prompt)))
                continue
            self._completed[idx] = list(toks)
            self.stats["rows_completed"] += 1
            self.stats["batch_tokens"] += len(toks)
            since_ckpt += 1
            if since_ckpt >= self._ckpt_every:
                self._write_checkpoint()
                since_ckpt = 0
        if since_ckpt or (self._ckpt_dir is not None
                          and not self.stats["checkpoints_written"]):
            self._write_checkpoint()
        missing = [i for i in range(n_total)
                   if i not in self._completed]
        if missing:
            raise RuntimeError(
                f"batch job finished with missing rows {missing[:8]}"
                f" (of {n_total}) — exactly-once ledger violated")
        return [self._completed[i] for i in range(n_total)]

    # ---------------------------------------------------------- reporting

    def progress(self) -> Dict[str, Any]:
        """Point-in-time progress summary (the manifest's stats block
        plus the ledger size)."""
        return {"job_id": self._job_id,
                "rows_in_ledger": len(self._completed),
                "pipeline_stats": list(self._pipeline_stats),
                **self.stats}


def run_batch_job(target, source, **kwargs) -> List[List[int]]:
    """One-call convenience: build and run a ``BatchInferenceJob``."""
    return BatchInferenceJob(target, source, **kwargs).run()
