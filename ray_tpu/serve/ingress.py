"""Per-deployment HTTP routing (ingress).

Capability parity with the reference's FastAPI ingress
(serve/api.py @serve.ingress + serve/http_adapters.py: a deployment
class whose methods are HTTP routes, path templates and all). No
FastAPI in this image, so the router is in-house: @serve.route marks
methods with a path template + verb set, @serve.ingress compiles the
route table onto the class and injects handle_route(), which the HTTP
proxy calls for any request with a subpath under the deployment.

Contract: routed methods are called as ``method(payload, **path_params)``
where payload is the JSON body (POST/PUT/PATCH) or the query-string
dict (GET/DELETE), or None when absent; ``{name}`` path segments bind
as keyword arguments (strings).
"""
from __future__ import annotations

import re
from typing import Any, Callable, Dict, Optional, Tuple

_SEG = re.compile(r"\{([a-zA-Z_][a-zA-Z0-9_]*)\}")


def route(path: str, methods=("GET",)) -> Callable:
    """Mark a deployment method as an HTTP route, e.g.
    ``@serve.route("/users/{uid}", methods=["GET"])``."""
    if not path.startswith("/"):
        raise ValueError(f"route path must start with '/': {path!r}")
    if isinstance(methods, str):
        raise TypeError(
            f"methods must be a list/tuple of verbs, not a string "
            f"(got {methods!r} — did you mean methods=[{methods!r}]?)")
    verbs = tuple(m.upper() for m in methods)
    known = {"GET", "POST", "PUT", "PATCH", "DELETE", "HEAD",
             "OPTIONS"}
    bad = [v for v in verbs if v not in known]
    if bad:
        raise ValueError(f"unknown HTTP methods {bad}")

    def deco(fn):
        fn.__serve_route__ = (path, verbs)
        return fn

    return deco


def _compile(path: str) -> "re.Pattern":
    out, last = [], 0
    for m in _SEG.finditer(path):
        out.append(re.escape(path[last:m.start()]))
        out.append(f"(?P<{m.group(1)}>[^/]+)")
        last = m.end()
    out.append(re.escape(path[last:]))
    return re.compile("^" + "".join(out) + "/?$")


def ingress(cls):
    """Class decorator compiling the @route table and injecting the
    dispatcher the proxy targets. Stacks under @serve.deployment:

        @serve.deployment
        @serve.ingress
        class Api:
            @serve.route("/items/{item_id}")
            def get_item(self, payload, item_id): ...
    """
    table = []
    for name in dir(cls):
        fn = getattr(cls, name, None)
        meta = getattr(fn, "__serve_route__", None)
        if meta is not None:
            path, verbs = meta
            table.append((_compile(path), verbs, name, path))
    if not table:
        raise ValueError(
            f"@serve.ingress on {cls.__name__}: no @serve.route-marked "
            "methods found")
    for reserved in ("handle_route", "serve_routes"):
        if reserved in vars(cls):
            raise ValueError(
                f"@serve.ingress on {cls.__name__}: the class already "
                f"defines {reserved}(), which ingress would overwrite")
    # Most-specific-first: fewer {param} segments beat more (so the
    # literal /users/me beats /users/{uid}), longer literal text
    # breaks ties.
    table.sort(key=lambda t: (len(_SEG.findall(t[3])),
                              -len(_SEG.sub("", t[3]))))
    cls.__serve_routes__ = table

    def handle_route(self, http_method: str, subpath: str,
                     payload: Optional[Any] = None):
        verb = http_method.upper()
        path_matched = False
        for pat, verbs, attr, _raw in type(self).__serve_routes__:
            m = pat.match(subpath)
            if m is None:
                continue
            path_matched = True
            if verb not in verbs:
                continue
            return getattr(self, attr)(payload, **m.groupdict())
        if path_matched:
            raise LookupError(
                f"405: method {verb} not allowed for {subpath!r}")
        raise LookupError(f"404: no route matches {subpath!r}")

    cls.handle_route = handle_route

    def serve_routes(self) -> Dict[str, Tuple[str, ...]]:
        """Route table introspection (shown by the dashboard)."""
        return {raw: verbs
                for _p, verbs, _a, raw in type(self).__serve_routes__}

    cls.serve_routes = serve_routes
    return cls
