"""Deterministic fault injection + quiescence invariants for the
LLM engine.

The engine's failure-containment contract (serve/engine.py) is only
worth anything if it can be PROVEN: after any mix of cancels,
deadlines, and injected faults, only the targeted requests fail,
survivors stay token-identical to greedy decode, and every resource
(allocator pages, prefix-cache refcounts, slots) returns to
baseline. This module is the harness for that proof — the serving
analogue of the cluster layer's fault tooling
(tests/test_fault_tooling.py).

Two pieces:

- ``FaultInjector`` — a test-only seam the engine consults at named
  sites. Plans are matched on (site, round, sid) and fire a bounded
  number of times, so a test can say "raise a readback error for
  slot 1 on round 3" and get exactly that, deterministically (the
  LRU ticks, round counter, and FIFO admission make engine rounds
  reproducible on CPU).
- ``EngineFault`` — the attribution envelope the engine's dispatch
  paths raise/convert to. ``culprit_sid``/``culprit_rid`` name the
  one request the fault belongs to; ``sids`` lists every slot that
  was participating in the failed dispatch so containment can
  requeue the innocent rest under the retry policy.
- ``check_quiesced`` — the invariant checker: asserts a drained
  engine is back at baseline (allocator occupancy == prefix-cache
  residency, zero refcounts, no orphaned slots, empty queues).

Sites the engine consults (all no-ops without an injector):

========================  ==================================================
site                      fires
========================  ==================================================
``alloc``                 before every ``BlockAllocator.alloc`` — a
                          matching ``exhaust`` plan makes it return None
                          (pool-dry behavior: evict/preempt/wait paths)
``dispatch_prefill``      per prefill row, before the batched call
``dispatch_decode``       per decode rider, before the batched call
``dispatch_spec``         per spec row, before the batched verify
``readback``              per rider, as its tokens are emitted host-side
``step``                  top of every scheduling round (global faults)
========================  ==================================================
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, List, Optional


class ReplicaKilled(Exception):
    """Marker exception for an injected whole-replica death (the
    moral equivalent of a device loss / process kill). Raised at the
    ``step`` site with no sid in scope, it escapes the scheduler
    loop's containment and lands in ``_fail_all`` — the engine stops
    and every request fails, exactly like a real replica crash."""


class EngineFault(Exception):
    """A fault attributable to (at most) one request.

    ``culprit_sid``/``culprit_rid``: the slot/request the fault
    belongs to (None = nobody in particular — e.g. a whole-dispatch
    transient). ``sids``: every slot participating in the failed
    dispatch; containment fails the culprit and requeues the rest
    under the bounded retry policy. ``original`` is the underlying
    error delivered to the failed request's consumer.
    """

    def __init__(self, original: BaseException,
                 culprit_sid: Optional[int] = None,
                 culprit_rid: Optional[int] = None,
                 sids: Optional[List[int]] = None):
        super().__init__(str(original))
        self.original = original
        self.culprit_sid = culprit_sid
        self.culprit_rid = culprit_rid
        self.sids = list(sids) if sids is not None else (
            [culprit_sid] if culprit_sid is not None else [])


@dataclasses.dataclass
class FaultPlan:
    """One planned fault. ``round`` is the engine's scheduling-round
    counter at which to start firing (None = any round); ``sid``
    restricts per-row sites to one slot (None = any row); ``times``
    bounds how often it fires (so recovery is observable)."""
    site: str
    kind: str = "raise"    # "raise" | "exhaust" | "sleep" | "hang"
    exc: Optional[BaseException] = None
    round: Optional[int] = None
    sid: Optional[int] = None
    times: int = 1
    sleep_s: float = 0.0
    fired: int = 0
    # "hang" plans park the firing thread on this event until
    # release_all() sets it — unlike "sleep", the wedge is cancellable
    event: Optional[threading.Event] = None

    def matches(self, site: str, rnd: int, sid: Optional[int]) -> bool:
        if self.fired >= self.times or site != self.site:
            return False
        if self.round is not None and rnd < self.round:
            return False
        if self.sid is not None and sid != self.sid:
            return False
        return True


class FaultInjector:
    """Deterministic fault seam. Construct, plan faults, hand to
    ``LLMEngine(fault_injector=...)``; inspect ``log`` afterwards."""

    def __init__(self):
        self.plans: List[FaultPlan] = []
        self.log: List[tuple] = []     # (site, round, sid, kind)

    # ------------------------------------------------------- planning

    def inject(self, site: str, *, exc: Optional[BaseException] = None,
               round: Optional[int] = None, sid: Optional[int] = None,
               times: int = 1) -> FaultPlan:
        """Raise ``exc`` (default RuntimeError) when ``site`` fires."""
        plan = FaultPlan(site=site, kind="raise",
                         exc=exc or RuntimeError(
                             f"injected fault at {site}"),
                         round=round, sid=sid, times=times)
        self.plans.append(plan)
        return plan

    def exhaust_alloc(self, *, round: Optional[int] = None,
                      times: int = 1) -> FaultPlan:
        """Make the next ``times`` allocator calls report a dry pool
        (returns None), exercising evict/preempt/wait recovery."""
        plan = FaultPlan(site="alloc", kind="exhaust", round=round,
                         times=times)
        self.plans.append(plan)
        return plan

    def kill_replica(self, *, round: Optional[int] = None
                     ) -> FaultPlan:
        """Plan a whole-replica death at scheduling round ``round``
        (None = next round): fires ``ReplicaKilled`` at the global
        ``step`` site, which bypasses per-slot containment and takes
        the entire engine down via ``_fail_all``. This is the pool's
        replica-failure drill — recovery (resubmission of unstarted
        requests, typed failure of partially-streamed ones) is the
        EnginePool's job, not the dead engine's."""
        plan = FaultPlan(site="step", kind="raise",
                         exc=ReplicaKilled(
                             "injected replica death"),
                         round=round, times=1)
        self.plans.append(plan)
        return plan

    def slow(self, site: str, sleep_s: float, *,
             round: Optional[int] = None, sid: Optional[int] = None,
             times: int = 1) -> FaultPlan:
        """Delay at ``site`` (deadline/timeout tests)."""
        plan = FaultPlan(site=site, kind="sleep", sleep_s=sleep_s,
                         round=round, sid=sid, times=times)
        self.plans.append(plan)
        return plan

    def hang(self, site: str, *, round: Optional[int] = None,
             sid: Optional[int] = None, times: int = 1) -> FaultPlan:
        """Wedge the firing thread at ``site`` until ``release_all()``
        (or ``plan.event.set()``). Unlike ``slow``'s un-cancellable
        ``time.sleep``, a hang can be RELEASED at teardown, so a
        watchdog-kill test doesn't leak a live sleeping thread — and
        the released zombie resuming inside ``step()`` is exactly the
        stale-generation vector the fencing tests need."""
        plan = FaultPlan(site=site, kind="hang", round=round, sid=sid,
                         times=times, event=threading.Event())
        self.plans.append(plan)
        return plan

    def release_all(self) -> int:
        """Release every hang plan (fired or not). Call this in EVERY
        chaos/teardown path — a test that kills a wedged engine still
        owns the thread parked inside it. Returns how many plans were
        newly released."""
        n = 0
        for plan in self.plans:
            if plan.kind == "hang" and plan.event is not None \
                    and not plan.event.is_set():
                plan.event.set()
                n += 1
        return n

    # ------------------------------------------------- engine-facing

    def fire(self, site: str, rnd: int, sid: Optional[int] = None,
             rid: Optional[int] = None) -> None:
        """Called by the engine at per-row/global sites. Raises the
        planned exception — wrapped in ``EngineFault`` with the row's
        attribution when a sid is in scope — or sleeps, or no-ops."""
        for plan in self.plans:
            if plan.kind == "exhaust" or not plan.matches(site, rnd,
                                                          sid):
                continue
            plan.fired += 1
            self.log.append((site, rnd, sid, plan.kind))
            if plan.kind == "sleep":
                time.sleep(plan.sleep_s)
                continue
            if plan.kind == "hang":
                # The log entry above lands BEFORE the wait, so a
                # watchdog test can confirm the wedge is in place.
                plan.event.wait()
                continue
            if sid is not None:
                raise EngineFault(plan.exc, culprit_sid=sid,
                                  culprit_rid=rid)
            raise plan.exc

    def exhausted(self, rnd: int) -> bool:
        """Allocator seam: True = pretend the pool is dry this call."""
        for plan in self.plans:
            if plan.kind == "exhaust" and plan.matches("alloc", rnd,
                                                       None):
                plan.fired += 1
                self.log.append(("alloc", rnd, None, "exhaust"))
                return True
        return False


def check_quiesced(eng, expect_cached_pages: Optional[int] = None
                   ) -> None:
    """Assert a drained engine returned to baseline. Valid once no
    request is queued or in flight (all handles resolved/failed).

    Invariants:
    - every slot is free (no orphaned slots after cancels/faults);
    - admission queue and readback queues are empty;
    - allocator occupancy == prefix-cache resident pages (pages are
      either free or owned by the tree — anything else leaked);
    - every cached page's refcount is 0 (no dangling slot refs);
    - the prefix tree's structural invariants hold.
    """
    live = [i for i, s in enumerate(eng.slots) if s is not None]
    assert not live, f"orphaned slots after drain: {live}"
    assert not eng._wait, \
        f"admission queue not drained: {len(eng._wait)} waiting"
    assert not eng._fetchq and not eng._pending_prefill, \
        "readback queues not drained"
    cached = (eng.prefix_cache.cached_pages
              if eng.prefix_cache is not None else 0)
    occ = eng.alloc.occupancy()
    assert occ == cached, (
        f"allocator occupancy {occ} != prefix-cache residency "
        f"{cached}: leaked pages {sorted(eng.alloc.leak_report())[:16]}")
    if expect_cached_pages is not None:
        assert cached == expect_cached_pages, (cached,
                                               expect_cached_pages)
    if eng.prefix_cache is not None:
        for page in list(eng.prefix_cache._nodes):
            r = eng.prefix_cache.ref_of(page)
            assert r == 0, f"cached page {page} still has refcount {r}"
        eng.prefix_cache.check_invariants()


def check_pool_quiesced(pool) -> None:
    """Pool-wide quiescence: every replica engine — healthy, draining,
    or dead — must individually pass ``check_quiesced``. A dead
    replica's ``_fail_all`` frees slot pages and drops prefix refs,
    so even a crash leaves allocator occupancy == cache residency;
    anything else is a leak the pool masked instead of contained."""
    for eng in pool.engines():
        check_quiesced(eng)
