"""The routing + resubmit core shared by EnginePool and FleetRouter.

The pool (one process) and the fleet (three processes over a
transport) make the same two decisions and must keep making them
identically:

1. **Selection** — given load reports for the live replicas, pick
   one: session stickiness → longest-prefix affinity (spill when the
   hot replica is saturated) → power-of-two-choices on least
   outstanding tokens. ``select_candidate`` is that policy as a pure
   function over ``Candidate`` records; the callers own state
   (replica tables, death noting, sticky maps) and metrics.

2. **Resubmit** — at-most-once recovery across replica deaths: a
   request that streamed ZERO tokens may be resubmitted
   token-identically; one that streamed anything fails typed
   ``EngineShutdown`` (a partial greedy stream cannot be replayed
   exactly-once). ``ResubmitPolicy`` is that guard: cancel check,
   resubmit budget, remaining-deadline carry-over, partial-stream
   refusal. ``PoolRequestHandle`` and ``FleetRequestHandle`` both
   subclass it.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.serve.errors import (DeadlineExceeded, EngineShutdown,
                                  RequestCancelled)
from ray_tpu.serve.prefix_cache import path_hashes


class Candidate:
    """One live, non-draining replica as the selection policy sees
    it: an opaque key (pool slot index or fleet replica id), its load
    report, and its KV page size (prefix digests are page-granular,
    so prompts are hashed per distinct ``page_size``)."""

    __slots__ = ("key", "report", "page_size")

    def __init__(self, key: Any, report: Dict[str, Any],
                 page_size: int):
        self.key = key
        self.report = report
        self.page_size = page_size

    def saturated(self) -> bool:
        rpt = self.report
        return (rpt.get("max_queued") is not None
                and rpt.get("queue_depth", 0) >= rpt["max_queued"])


def select_candidate(cands: List[Candidate], prompt: List[int], *,
                     sticky_key: Any = None, rng,
                     hash_fn: Callable[[List[int], int], List[int]]
                     = path_hashes
                     ) -> Tuple[Optional[Candidate], Dict[str, Any]]:
    """Pick a candidate, or ``(None, {"hints": [...]})`` when nothing
    can admit (hints are the candidates' shed Retry-After values; an
    empty list means there was no live candidate at all)."""
    if not cands:
        return None, {"hints": []}

    open_cands = [c for c in cands if not c.saturated()]
    if not open_cands:
        return None, {"hints": [
            c.report.get("shed_retry_after_s", 0.0) for c in cands]}

    # longest cached prefix per candidate, page-granular
    hashes_by_pg: Dict[int, List[int]] = {}
    match_pages: Dict[Any, int] = {}
    for c in cands:
        digest = c.report.get("prefix_digest") or ()
        if not digest:
            match_pages[c.key] = 0
            continue
        hs = hashes_by_pg.get(c.page_size)
        if hs is None:
            hs = hashes_by_pg[c.page_size] = hash_fn(prompt,
                                                     c.page_size)
        k = 0
        for h in hs:
            if h not in digest:
                break
            k += 1
        match_pages[c.key] = k

    outstanding = {c.key: c.report.get("outstanding_tokens", 0)
                   for c in cands}

    # 1. session stickiness
    if sticky_key is not None:
        for c in open_cands:
            if c.key == sticky_key:
                return c, {"kind": "sticky",
                           "pages": match_pages.get(c.key, 0)}

    # 2. longest-prefix affinity (scored over ALL live candidates: a
    #    saturated best target means spill, not a blind miss)
    best: Optional[Candidate] = None
    best_pages = 0
    for c in cands:
        k = match_pages.get(c.key, 0)
        if k > best_pages or (k == best_pages and k > 0
                              and best is not None
                              and outstanding[c.key]
                              < outstanding[best.key]):
            best, best_pages = c, k
    spilled = False
    if best is not None and best_pages > 0:
        if not best.saturated():
            return best, {"kind": "affinity", "pages": best_pages}
        spilled = True         # hot candidate is full: overflow

    # 3. power-of-two-choices on least outstanding tokens
    if len(open_cands) == 1:
        pick = open_cands[0]
    else:
        a, b = rng.sample(open_cands, 2)
        pick = a if (outstanding[a.key], a.key) <= (
            outstanding[b.key], b.key) else b
    return pick, {"kind": "p2c", "spilled": spilled,
                  "pages": match_pages.get(pick.key, 0)}


class ResubmitPolicy:
    """At-most-once resubmission state shared by the pool's and the
    fleet's request handles: generated-token ledger, resubmit budget,
    deadline carry-over, and the typed failures for every way a
    recovery can be refused. Subclasses own submission (how a request
    reaches a replica) and streaming (how tokens come back)."""

    def __init__(self, prompt: List[int], max_new_tokens: int,
                 deadline_s: Optional[float],
                 session_id: Optional[str],
                 trace_id: Optional[str],
                 max_resubmits: int):
        self._prompt = list(prompt)
        self._mnt = max_new_tokens
        self._deadline_s = deadline_s
        self._session_id = session_id
        self._trace_id = trace_id
        self._max_resubmits = max_resubmits
        self._t0 = time.monotonic()
        self._t_first: Optional[float] = None
        self._generated: List[int] = []
        self._resubmits = 0
        self._error: Optional[BaseException] = None
        self._finished = False
        self._cancelled = False

    # ------------------------------------------------------- consuming

    def result(self) -> List[int]:
        """Block until completion; return all generated token ids."""
        for _ in self.stream():
            pass
        return list(self._generated)

    def stream(self):           # pragma: no cover - abstract
        raise NotImplementedError

    # ------------------------------------------------------ inspection

    @property
    def done(self) -> bool:
        return self._finished or self._error is not None

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    @property
    def ttft_s(self) -> Optional[float]:
        """Submit-to-first-token as the CLIENT saw it — spans
        resubmissions, unlike the per-engine stamp."""
        if self._t_first is None:
            return None
        return self._t_first - self._t0

    @property
    def resubmits(self) -> int:
        return self._resubmits

    # -------------------------------------------------------- internal

    def _fail(self, err: BaseException) -> None:
        self._error = err

    def _note_token(self, tok: int) -> None:
        if self._t_first is None:
            self._t_first = time.monotonic()
        self._generated.append(tok)

    def _remaining_deadline(self,
                            cause: BaseException) -> Optional[float]:
        if self._deadline_s is None:
            return None
        left = self._deadline_s - (time.monotonic() - self._t0)
        if left <= 0:
            err = DeadlineExceeded(
                "deadline elapsed while recovering from a replica "
                "death")
            self._fail(err)
            raise err from cause
        return left

    def _partial_stream_error(self, where: str,
                              cause: BaseException) -> EngineShutdown:
        err = EngineShutdown(
            f"replica {where} died after {len(self._generated)} "
            f"streamed tokens; a partial stream cannot be replayed "
            f"at-most-once")
        self._fail(err)
        return err

    def _check_resubmit(self,
                        cause: BaseException) -> Optional[float]:
        """Gate one resubmission attempt: raises typed when recovery
        is impossible (cancelled / budget exhausted / deadline gone),
        otherwise bumps the counter and returns the remaining
        deadline to carry into the retry."""
        if self._cancelled:
            err = RequestCancelled("request cancelled")
            self._fail(err)
            raise err from cause
        if self._resubmits >= self._max_resubmits:
            err = EngineShutdown(
                f"request resubmitted {self._resubmits} times "
                f"without completing; giving up")
            self._fail(err)
            raise err from cause
        deadline = self._remaining_deadline(cause)
        self._resubmits += 1
        return deadline
