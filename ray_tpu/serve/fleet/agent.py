"""ReplicaAgent: one engine per host, lease-fenced, self-policing.

The data-plane half of the fleet split. An agent owns exactly one
``LLMEngine``, registers it with the FleetDirectory, and keeps the
lease alive by renewing every third of the TTL (each renewal
piggybacks the engine's prefix digest + load report — the directory
is how the router sees this host). Three failure behaviors carry the
correctness story:

- **Self-fencing.** The agent tracks its own lease deadline from its
  own clock. When renewals stop landing (partition, directory crash
  + slow recovery) and the deadline passes, the agent fences ITSELF:
  new submits fail typed ``AgentFenced`` and every in-flight request
  is cancelled. By the time the router (via the directory) declares
  this replica dead and resubmits its requests elsewhere, the
  partitioned agent has already stopped producing tokens — a
  resubmitted request can never be double-served, whichever side of
  the partition you watch from. A fenced agent re-joins by
  re-registering under ``generation+1`` with a fresh request table.

- **Idempotent admission.** Every submit carries a router-minted
  request key; duplicate delivery (retried or transport-duplicated
  frames) returns the EXISTING request id instead of admitting
  twice. Polls are cursor-based, so a duplicated poll re-reads
  instead of double-consuming. Together these make the transport's
  at-least-once retries safe on an at-most-once engine.

- **Local watchdog.** ``watchdog.AgentWatchdog`` probes the engine's
  progress heartbeat; a wedge is flight-dumped, force-killed, and
  REPORTED on the next renewal (``wedged=True``) before the agent
  rebuilds its engine under a new generation — the pool-side ladder,
  relocated to the only process that can still see the engine.

``ScriptedEngine`` is a deterministic no-jax stand-in engine
(``scripted_completion`` is its pure ground truth) so the
cross-process tier-1 smoke runs in milliseconds; the real campaign
runs llama_tiny fp32 greedy in every agent process.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.serve import kv_migration, obs
from ray_tpu.serve.errors import (EngineDraining, EngineShutdown,
                                  RequestCancelled)
from ray_tpu.serve.fleet import wire
from ray_tpu.serve.fleet.directory import DirectoryClient
from ray_tpu.serve.fleet.transport import (SocketTransport,
                                           Transport)
from ray_tpu.serve.fleet.wire import (AgentFenced, KVPullAborted,
                                      StaleFencingToken)

ACTIVE = "active"
FENCED = "fenced"


def scripted_completion(prompt: List[int],
                        max_new_tokens: int) -> List[int]:
    """Pure deterministic completion: the ScriptedEngine's ground
    truth, computable in any process without the model."""
    x = 0
    for t in prompt:
        x = (x * 31 + int(t) + 7) % 100003
    out = []
    for _ in range(max_new_tokens):
        x = (x * 1103515245 + 12345) % 100003
        out.append(x % 997)
    return out


class _ScriptedHandle:
    def __init__(self, eng: "ScriptedEngine", prompt: List[int],
                 n: int):
        self._eng = eng
        self._tokens = scripted_completion(prompt, n)
        self._cancelled = False

    def stream(self):
        for tok in self._tokens:
            if self._cancelled:
                raise RequestCancelled("request cancelled")
            if self._eng._stopped:
                raise (self._eng._kill_err
                       or EngineShutdown("engine stopped"))
            time.sleep(self._eng.token_delay_s)
            self._eng._hb = time.monotonic()
            yield tok

    def result(self) -> List[int]:
        return list(self.stream())

    def cancel(self) -> bool:
        self._cancelled = True
        return True


class ScriptedEngine:
    """Deterministic, model-free engine with the surface the agent
    (and the routing core) needs: submit/stream, load_report with
    heartbeat + digest keys, drain/force_kill/shutdown. Token i of a
    request is a pure function of the prompt, so cross-process
    token-identity checks have one right answer with zero startup
    cost."""

    def __init__(self, *, page_size: int = 8,
                 token_delay_s: float = 0.002,
                 max_queued: Optional[int] = None):
        self.Pg = page_size
        self.token_delay_s = token_delay_s
        self.max_queued = max_queued
        self._stopped = False
        self._draining = False
        self._kill_err: Optional[BaseException] = None
        self._hb = time.monotonic()
        self._active = 0
        self._lock = threading.Lock()

    def start(self) -> "ScriptedEngine":
        return self

    def submit(self, prompt_ids, max_new_tokens: int = 16,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None,
               pull: Optional[Dict[str, Any]] = None
               ) -> _ScriptedHandle:
        # ``pull`` accepted for surface parity with LLMEngine and
        # ignored: the scripted engine has no KV to migrate.
        if self._stopped:
            raise EngineShutdown("engine stopped")
        if self._draining:
            raise EngineDraining("engine draining")
        with self._lock:
            self._active += 1
        self._hb = time.monotonic()
        return _ScriptedHandle(self, list(prompt_ids),
                               max_new_tokens)

    def request_done(self) -> None:
        with self._lock:
            self._active = max(0, self._active - 1)

    def load_report(self) -> Dict[str, Any]:
        with self._lock:
            active = self._active
        return {"free_slots": max(0, 4 - active), "total_slots": 4,
                "free_pages": 64, "queue_depth": active,
                "outstanding_tokens": active * 8,
                "max_queued": self.max_queued,
                "shed_retry_after_s": 0.05, "shed_total": 0,
                "ttft_ewma_s": None, "draining": self._draining,
                "stopped": self._stopped,
                "heartbeat_age_s": time.monotonic() - self._hb,
                "fetchq_depth": 0, "pending_prefills": 0,
                "overlap": False, "has_work": active > 0, "tp": 1,
                "prefix_digest": frozenset()}

    def drain(self, timeout_s: float = 5.0) -> bool:
        self._draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._active == 0:
                    return True
            time.sleep(0.005)
        with self._lock:
            return self._active == 0

    def force_kill(self, err: Optional[BaseException] = None) -> None:
        self._kill_err = err
        self._stopped = True

    def shutdown(self) -> None:
        self._stopped = True


class ReplicaAgent:
    """One engine + its lease, behind a transport handler."""

    def __init__(self, replica_id: str,
                 engine_factory: Callable[[int], Any],
                 directory: DirectoryClient, *,
                 addr: Optional[List[Any]] = None,
                 generation: int = 0,
                 renew_period_s: Optional[float] = None,
                 stall_deadline_s: Optional[float] = None,
                 flight_dir: Any = None,
                 register_patience_s: float = 60.0,
                 peer_transport_factory: Optional[
                     Callable[[Any], Transport]] = None,
                 time_fn: Callable[[], float] = time.monotonic):
        self.replica_id = replica_id
        self._factory = engine_factory
        self._directory = directory
        # how this agent dials a DONOR peer for a KV pull: defaults
        # to a TCP dial of the hint's ["tcp", host, port] addr; tests
        # inject loopback resolution here
        self._peer_tf = peer_transport_factory
        self.addr = addr if addr is not None else ["loopback",
                                                   replica_id]
        self.generation = int(generation)
        self._renew_period_s = renew_period_s
        self._register_patience_s = float(register_patience_s)
        self._stall_deadline_s = stall_deadline_s
        self.flight_dir = flight_dir
        self._now = time_fn
        self._lock = threading.Lock()
        self.engine: Any = None
        self.state = ACTIVE
        self.fence = 0
        self.lease_ttl_s = 0.0
        self._lease_deadline = 0.0
        self._draining = False
        self._partition_until = 0.0
        self._wedge_err: Optional[BaseException] = None
        self._reqs: Dict[str, Dict[str, Any]] = {}
        self._by_key: Dict[str, str] = {}
        self._seq = 0
        self._stop = threading.Event()
        self._renew_thread: Optional[threading.Thread] = None
        self._watchdog = None
        # Donor side of cross-replica KV migration
        # (serve/kv_migration.py): rebuilt with the engine after a
        # wedge so a transfer table can never outlive its pool. The
        # env knob stretches each chunk export so chaos harnesses can
        # kill a donor deterministically MID-pull.
        self._kv_donor: Optional[kv_migration.KVDonor] = None
        self._kv_chunk_delay_s = float(
            os.environ.get("RAY_TPU_KV_CHUNK_DELAY_S", "0") or 0)
        self.events = obs.EventLog(1024, name=f"agent-{replica_id}")
        self.counters = {"submits": 0, "dup_submits": 0,
                         "refused_fenced": 0, "refused_stale_fence":
                         0, "polls": 0, "self_fences": 0,
                         "reregisters": 0, "wedges": 0,
                         "cancelled_on_fence": 0}

    # ------------------------------------------------------- lifecycle

    def start(self) -> "ReplicaAgent":
        if self.engine is None:
            self.engine = self._factory(self.generation)
            if hasattr(self.engine, "start"):
                self.engine.start()
        self._wire_engine_kv()
        # the control plane may be mid-failover at boot (old primary
        # dead, standby not yet promoted): every endpoint then answers
        # TransportError or NotPrimary. That is a TRANSIENT condition
        # — retry through it. Typed rejections (tombstoned
        # generation) are permanent and propagate immediately.
        deadline = self._now() + self._register_patience_s
        while True:
            try:
                self._register(min_fence=0)
                break
            except (wire.StaleFencingToken, wire.AgentFenced):
                raise
            except Exception:   # noqa: BLE001
                if self._now() >= deadline:
                    raise
                self._stop.wait(0.2)
        if self._renew_thread is None:
            self._renew_thread = threading.Thread(
                target=self._renew_loop,
                name=f"agent-renew-{self.replica_id}", daemon=True)
            self._renew_thread.start()
        if (self._stall_deadline_s is not None
                and self._watchdog is None):
            from ray_tpu.serve.watchdog import AgentWatchdog
            self._watchdog = AgentWatchdog(
                lambda: self.engine, self._on_wedge,
                stall_deadline_s=self._stall_deadline_s,
                flight_dir=self.flight_dir).run()
        return self

    def shutdown(self) -> None:
        self._stop.set()
        if self._watchdog is not None:
            self._watchdog.stop()
        t = self._renew_thread
        if t is not None:
            t.join(timeout=5.0)
            self._renew_thread = None
        if self.engine is not None:
            try:
                self.engine.shutdown()
            except Exception:
                pass

    # ----------------------------------------------------- lease logic

    def _register(self, min_fence: int) -> None:
        r = self._directory.register(
            self.replica_id, self.addr, self.generation,
            page_size=getattr(self.engine, "Pg", 0),
            min_fence=min_fence,
            role=getattr(self.engine, "role", "unified"))
        with self._lock:
            self.fence = int(r["fence"])
            self.lease_ttl_s = float(r["lease_ttl_s"])
            self._lease_deadline = self._now() + self.lease_ttl_s
            self.state = ACTIVE
        self.events.append("registered",
                           data={"fence": self.fence,
                                 "generation": self.generation})

    def partitioned(self) -> bool:
        return self._now() < self._partition_until

    def reachable(self) -> bool:
        """SocketServer gate: False while partitioned — inbound
        frames are dropped without a response."""
        return not self.partitioned()

    def _renew_payload(self) -> Dict[str, Any]:
        digest: List[int] = []
        load: Dict[str, Any] = {}
        try:
            rpt = dict(self.engine.load_report())
            digest = sorted(rpt.pop("prefix_digest", ()) or ())
            load = _json_safe(rpt)
        except Exception:
            pass
        return {"digest": digest, "load": load}

    def _renew_loop(self) -> None:
        while not self._stop.is_set():
            period = (self._renew_period_s
                      if self._renew_period_s is not None
                      else max(0.02, self.lease_ttl_s / 3.0))
            self._stop.wait(period)
            if self._stop.is_set():
                return
            self.renew_once()

    def renew_once(self) -> bool:
        """One renewal attempt + the self-fencing judgement. Split
        out of the loop so tests can drive it deterministically."""
        wedge = self._wedge_err
        if not self.partitioned() and self.state == ACTIVE:
            adv = self._renew_payload()
            t_call = self._now()
            try:
                self._directory.renew(
                    self.replica_id, self.fence,
                    digest=adv["digest"], load=adv["load"],
                    wedged=wedge is not None)
                with self._lock:
                    self._lease_deadline = t_call + self.lease_ttl_s
                if wedge is not None:
                    self._rebuild_after_wedge()
                return True
            except (wire.UnknownMember, StaleFencingToken):
                # directory restarted (lost our membership) or we
                # were superseded: membership recovers from agent
                # re-advertisement — re-register, SAME generation
                # (requests in flight are healthy; a directory
                # restart must be invisible to clients)
                try:
                    self._reregister(bump_generation=False)
                except Exception:
                    pass
                return False
            except Exception:
                pass    # transport trouble: judged below
        if (self.state == ACTIVE
                and self._now() > self._lease_deadline):
            self._self_fence("lease lapsed without renewal")
        if self.state == FENCED and not self.partitioned():
            # fenced agents re-join as a fresh incarnation
            try:
                self._reregister(bump_generation=True)
            except Exception:
                pass
        return False

    def _reregister(self, bump_generation: bool) -> None:
        old_fence = self.fence
        if bump_generation:
            self.generation += 1
            with self._lock:
                self._reqs.clear()
                self._by_key.clear()
        self._register(min_fence=old_fence)
        self.counters["reregisters"] += 1
        self.events.append(
            "reregistered",
            data={"fence": self.fence,
                  "generation": self.generation,
                  "bumped": bump_generation})

    def _self_fence(self, reason: str) -> None:
        with self._lock:
            if self.state == FENCED:
                return
            self.state = FENCED
            self.counters["self_fences"] += 1
            active = [rec for rec in self._reqs.values()
                      if not rec["done"] and rec["error"] is None]
            for rec in active:
                rec["error"] = wire.err(AgentFenced(
                    f"agent {self.replica_id} self-fenced: "
                    f"{reason}"))["error"]
                self.counters["cancelled_on_fence"] += 1
        # cancel outside the lock: handle.cancel takes engine locks
        for rec in active:
            try:
                rec["handle"].cancel()
            except Exception:
                pass
        self.events.append("self_fence",
                           data={"reason": reason,
                                 "fence": self.fence,
                                 "generation": self.generation,
                                 "cancelled": len(active)})
        if self.flight_dir:
            try:
                obs.dump_flight_bundle(
                    self.flight_dir,
                    f"self-fenced-{self.replica_id}",
                    engine=self.engine, pool=self,
                    extra={"replica_id": self.replica_id,
                           "reason": reason, "fence": self.fence,
                           "generation": self.generation,
                           "lease_overdue_s": round(
                               self._now() - self._lease_deadline,
                               4),
                           "cancelled_in_flight": len(active)})
            except Exception:
                pass

    def _on_wedge(self, err: BaseException) -> None:
        self._wedge_err = err
        self.counters["wedges"] += 1
        self.events.append("wedged", data={"err": str(err)})

    def _rebuild_after_wedge(self) -> None:
        """Wedge was reported on a successful renewal: replace the
        corpse under a new generation and re-register."""
        self._wedge_err = None
        self.generation += 1
        with self._lock:
            self._reqs.clear()
            self._by_key.clear()
        old = self.engine
        self.engine = self._factory(self.generation)
        if hasattr(self.engine, "start"):
            self.engine.start()
        self._wire_engine_kv()
        try:
            if old is not None:
                old.shutdown()
        except Exception:
            pass
        self._reregister_engine_swap()

    def _reregister_engine_swap(self) -> None:
        try:
            self._register(min_fence=self.fence)
            self.counters["reregisters"] += 1
        except Exception:
            pass

    # ----------------------------------------------------- RPC surface

    def handle(self, method: str, args: Dict[str, Any],
               trace_id: Optional[str] = None) -> Any:
        fn = getattr(self, "rpc_" + method, None)
        if fn is None:
            raise EngineShutdown(f"agent has no method {method}")
        if method == "submit":
            return fn(trace_id=trace_id, **args)
        return fn(**args)

    def rpc_ping(self) -> Dict[str, Any]:
        return {"ok": True, "replica_id": self.replica_id,
                "generation": self.generation, "state": self.state}

    def rpc_submit(self, key: str, prompt_ids: List[int],
                   max_new_tokens: int,
                   deadline_s: Optional[float] = None,
                   fence: Optional[int] = None,
                   pull: Optional[Dict[str, Any]] = None,
                   trace_id: Optional[str] = None) -> Dict[str, Any]:
        if self.state == FENCED:
            self.counters["refused_fenced"] += 1
            raise AgentFenced(
                f"agent {self.replica_id} is fenced (lease lapsed); "
                f"refusing admission")
        if self._draining:
            raise EngineDraining(
                f"agent {self.replica_id} is draining")
        if fence is not None and int(fence) != self.fence:
            self.counters["refused_stale_fence"] += 1
            raise StaleFencingToken(
                f"submit quoted fence {fence}; agent "
                f"{self.replica_id} holds fence {self.fence}")
        with self._lock:
            rid = self._by_key.get(key)
            if rid is not None:
                # duplicate delivery (transport retry or injected
                # dup): hand back the SAME request, admit nothing
                self.counters["dup_submits"] += 1
                return {"rid": rid, "dedup": True,
                        "generation": self.generation}
        kw: Dict[str, Any] = dict(max_new_tokens=int(max_new_tokens),
                                  deadline_s=deadline_s)
        if trace_id is not None:
            kw["trace_id"] = trace_id
        if pull is not None:
            # router's cross-replica prefix hint: a HINT only — the
            # engine declines it whenever its local cache already
            # covers the prefix, and any pull failure degrades to
            # plain prefill, so a stale hint costs nothing but time
            kw["pull"] = pull
        inner = self.engine.submit(list(prompt_ids), **kw)
        with self._lock:
            # lost the race to a duplicate that admitted first?
            # (submit is serialized per connection, but loopback +
            # dup wrapper can interleave): keep the first admission
            prev = self._by_key.get(key)
            if prev is not None:
                self.counters["dup_submits"] += 1
                rid = prev
                dup_inner = inner
            else:
                self._seq += 1
                rid = (f"{self.replica_id}.g{self.generation}"
                       f".{self._seq}")
                rec = {"rid": rid, "key": key, "tokens": [],
                       "done": False, "error": None,
                       "handle": inner, "trace_id": trace_id}
                self._reqs[rid] = rec
                self._by_key[key] = rid
                dup_inner = None
        if dup_inner is not None:
            try:
                dup_inner.cancel()
            except Exception:
                pass
            return {"rid": rid, "dedup": True,
                    "generation": self.generation}
        self.counters["submits"] += 1
        self.events.append("submit", rid=rid,
                           data={"trace_id": trace_id, "key": key})
        threading.Thread(target=self._pump, args=(rec,),
                         name=f"agent-pump-{rid}",
                         daemon=True).start()
        return {"rid": rid, "dedup": False,
                "generation": self.generation}

    def _pump(self, rec: Dict[str, Any]) -> None:
        """Drain the engine stream into the poll buffer."""
        try:
            first = True
            for tok in rec["handle"].stream():
                if first:
                    first = False
                    self.events.append(
                        "first_token", rid=rec["rid"],
                        data={"trace_id": rec["trace_id"]})
                with self._lock:
                    rec["tokens"].append(int(tok))
            with self._lock:
                rec["done"] = True
            self.events.append(
                "retire", rid=rec["rid"],
                data={"trace_id": rec["trace_id"],
                      "n_tokens": len(rec["tokens"])})
        except BaseException as e:
            with self._lock:
                if rec["error"] is None:
                    rec["error"] = wire.err(e)["error"]
            self.events.append(
                "failed", rid=rec["rid"],
                data={"trace_id": rec["trace_id"],
                      "error": type(e).__name__})
        finally:
            done_hook = getattr(self.engine, "request_done", None)
            if done_hook is not None:
                try:
                    done_hook()
                except Exception:
                    pass

    def rpc_poll(self, rid: str, cursor: int = 0) -> Dict[str, Any]:
        self.counters["polls"] += 1
        with self._lock:
            rec = self._reqs.get(rid)
            if rec is None:
                raise EngineShutdown(
                    f"unknown rid {rid}: the agent re-registered "
                    f"under a new generation (its requests were "
                    f"fenced)")
            cursor = max(0, int(cursor))
            return {"tokens": rec["tokens"][cursor:],
                    "done": rec["done"], "error": rec["error"],
                    "generation": self.generation}

    def rpc_cancel(self, rid: str) -> Dict[str, Any]:
        with self._lock:
            rec = self._reqs.get(rid)
        if rec is None:
            return {"cancelled": False}
        try:
            return {"cancelled": bool(rec["handle"].cancel())}
        except Exception:
            return {"cancelled": False}

    # ------------------------------------------------- KV migration

    def _wire_engine_kv(self) -> None:
        """(Re)build the KV donor for the CURRENT engine and inject
        the requester-side fetcher. Runs at start and after every
        wedge rebuild: a donor kept across a rebuild would export
        pages from a pool that no longer exists, and an in-flight
        transfer against the old engine now lands on an empty
        transfer table — a typed ``KVPullAborted``, never stale
        bytes."""
        eng = self.engine
        if eng is None or not hasattr(eng, "kv_migration_stats"):
            self._kv_donor = None
            return
        self._kv_donor = kv_migration.KVDonor(
            eng, chunk_delay_s=self._kv_chunk_delay_s)
        eng.kv_fetcher = self._kv_fetch

    def _kv_donor_or_abort(self) -> "kv_migration.KVDonor":
        if self.state == FENCED:
            raise KVPullAborted(
                f"donor {self.replica_id} is fenced; its pages may "
                f"be reclaimed at any moment")
        donor = self._kv_donor
        if donor is None:
            raise KVPullAborted(
                f"agent {self.replica_id} has no KV pool to donate "
                f"from")
        return donor

    def rpc_kv_pull_begin(self,
                          hashes: List[int]) -> Dict[str, Any]:
        return self._kv_donor_or_abort().begin(list(hashes))

    def rpc_kv_pull_chunk(self, xfer_id: str,
                          chunk_idx: int) -> Dict[str, Any]:
        return self._kv_donor_or_abort().chunk(str(xfer_id),
                                               int(chunk_idx))

    def rpc_kv_pull_end(self, xfer_id: str) -> Dict[str, Any]:
        donor = self._kv_donor
        if donor is None:
            return {"released": False}
        return donor.end(str(xfer_id))

    def _kv_fetch(self,
                  pull: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Requester-side fetcher the engine calls from its PULLING
        phase: dial the donor named in the pull hint and run the
        chunked pull protocol. Returns None on ANY failure — the
        engine then requeues for plain prefill, so a pull is never
        worse than not having tried."""
        addr = pull.get("addr")
        if self._peer_tf is not None:
            try:
                t = self._peer_tf(tuple(addr or ()))
            except Exception:
                return None
        elif (isinstance(addr, (list, tuple)) and len(addr) == 3
                and addr[0] == "tcp"):
            t = SocketTransport((addr[1], int(addr[2])))
        else:
            return None
        try:
            return kv_migration.pull_prefix(
                lambda m, a: t.call(m, a),
                pull.get("hashes") or [],
                stats=getattr(self.engine, "kv_migration_stats",
                              None))
        except Exception:
            return None
        finally:
            t.close()

    def rpc_load_report(self) -> Dict[str, Any]:
        rpt = dict(self.engine.load_report())
        rpt["prefix_digest"] = sorted(rpt.get("prefix_digest", ())
                                      or ())
        rpt.update(replica_id=self.replica_id,
                   generation=self.generation, fence=self.fence,
                   state=self.state)
        return _json_safe(rpt)

    def rpc_stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"counters": dict(self.counters),
                               "state": self.state,
                               "generation": self.generation,
                               "fence": self.fence}
        eng = self.engine
        for name in ("stats", "ttfts_s", "prefix_stats",
                     "spec_stats", "lifecycle_stats",
                     "kv_migration_stats"):
            try:
                v = getattr(eng, name, None)
                v = v() if callable(v) else v
                out[name] = _json_safe(v)
            except Exception:
                out[name] = None
        if self._watchdog is not None:
            out["watchdog"] = self._watchdog.stats()
        return out

    def rpc_drain(self, timeout_s: float = 5.0) -> Dict[str, Any]:
        """Graceful scale-down: refuse admissions, wait for in-flight
        work, deregister."""
        self._draining = True
        clean = True
        try:
            if hasattr(self.engine, "drain"):
                clean = bool(self.engine.drain(timeout_s))
        except Exception:
            clean = False
        deadline = self._now() + max(0.0, timeout_s)
        while self._now() < deadline:
            with self._lock:
                if all(rec["done"] or rec["error"] is not None
                       for rec in self._reqs.values()):
                    break
            time.sleep(0.005)
        try:
            self._directory.deregister(self.replica_id, self.fence)
        except Exception:
            clean = False
        self.events.append("drained", data={"clean": clean})
        return {"clean": clean}

    def rpc_quiesce(self) -> Dict[str, Any]:
        """Remote quiescence probe: the cross-process face of
        ``faults.check_quiesced``."""
        eng = self.engine
        if hasattr(eng, "alloc"):
            from ray_tpu.serve.faults import check_quiesced
            try:
                check_quiesced(eng)
                return {"ok": True}
            except AssertionError as e:
                return {"ok": False, "error": str(e)}
        with self._lock:
            pending = [r for r, rec in self._reqs.items()
                       if not rec["done"] and rec["error"] is None]
        return {"ok": not pending,
                "error": (f"{len(pending)} requests still in "
                          f"flight" if pending else None)}

    def rpc_fence(self, reason: str = "forced by operator"
                  ) -> Dict[str, Any]:
        self._self_fence(reason)
        return {"state": self.state}

    def rpc_inject_partition(self,
                             duration_s: float) -> Dict[str, Any]:
        """Chaos seam: cut this agent off both ways — inbound frames
        drop (``reachable`` gate) and outbound renewals stop — for
        ``duration_s`` seconds."""
        self._partition_until = self._now() + float(duration_s)
        self.events.append("partitioned",
                           data={"duration_s": duration_s})
        return {"until_s": duration_s}

    def rpc_telemetry(self, cursor: int = 0,
                      limit: int = 256) -> Dict[str, Any]:
        """The fleet scrape seam (serve/fleet/telemetry.py): this
        process's Prometheus exposition, a cursored window of its
        event log, and a clock sample the collector turns into an
        NTP-style offset estimate. Served even while FENCED — an
        operator needs telemetry from a sick member most of all."""
        from ray_tpu.util import metrics
        window, next_cursor, dropped = obs.event_window(
            self.events.snapshot(), self.events.total, cursor, limit)
        return {
            "role": "agent",
            "replica_id": self.replica_id,
            "generation": self.generation,
            "fence": self.fence,
            "state": self.state,
            "pid": os.getpid(),
            "clock": {"mono": time.monotonic(),
                      "wall": time.time()},
            "metrics_text": metrics.prometheus_text(),
            "events": obs.as_dicts(window),
            "cursor": next_cursor,
            "events_total": self.events.total,
            "dropped": dropped,
        }

    def rpc_shutdown(self) -> Dict[str, Any]:
        threading.Thread(target=self.shutdown, daemon=True).start()
        return {"ok": True}

    # ---------------------------------------------------- obs plumbing

    def pool_stats(self) -> Dict[str, Any]:
        """Lets ``obs.dump_flight_bundle(pool=agent)`` record the
        agent the way it records a pool."""
        return {"replica_id": self.replica_id, "state": self.state,
                "generation": self.generation, "fence": self.fence,
                "counters": dict(self.counters)}


def _json_safe(v: Any) -> Any:
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_json_safe(x) for x in v]
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


class AgentClient:
    """Typed client wrapper over any transport to an agent."""

    def __init__(self, transport: Transport,
                 timeout_s: float = 5.0):
        self._t = transport
        self._timeout_s = timeout_s

    def ping(self) -> Dict[str, Any]:
        return self._t.call("ping", {}, timeout_s=self._timeout_s)

    def submit(self, key: str, prompt_ids: List[int],
               max_new_tokens: int,
               deadline_s: Optional[float] = None,
               fence: Optional[int] = None,
               pull: Optional[Dict[str, Any]] = None,
               trace_id: Optional[str] = None,
               timeout_s: Optional[float] = None) -> Dict[str, Any]:
        args = {"key": key, "prompt_ids": list(prompt_ids),
                "max_new_tokens": max_new_tokens,
                "deadline_s": deadline_s, "fence": fence}
        if pull is not None:
            args["pull"] = pull
        return self._t.call(
            "submit", args,
            timeout_s=(timeout_s if timeout_s is not None
                       else self._timeout_s),
            trace_id=trace_id)

    def kv_pull_begin(self, hashes: List[int]) -> Dict[str, Any]:
        return self._t.call("kv_pull_begin",
                            {"hashes": list(hashes)},
                            timeout_s=self._timeout_s)

    def kv_pull_chunk(self, xfer_id: str,
                      chunk_idx: int) -> Dict[str, Any]:
        return self._t.call(
            "kv_pull_chunk",
            {"xfer_id": xfer_id, "chunk_idx": chunk_idx},
            timeout_s=self._timeout_s)

    def kv_pull_end(self, xfer_id: str) -> Dict[str, Any]:
        return self._t.call("kv_pull_end", {"xfer_id": xfer_id},
                            timeout_s=self._timeout_s)

    def poll(self, rid: str, cursor: int = 0,
             trace_id: Optional[str] = None,
             timeout_s: Optional[float] = None) -> Dict[str, Any]:
        return self._t.call(
            "poll", {"rid": rid, "cursor": cursor},
            timeout_s=(timeout_s if timeout_s is not None
                       else self._timeout_s),
            trace_id=trace_id)

    def cancel(self, rid: str) -> Dict[str, Any]:
        return self._t.call("cancel", {"rid": rid},
                            timeout_s=self._timeout_s)

    def load_report(self) -> Dict[str, Any]:
        return self._t.call("load_report", {},
                            timeout_s=self._timeout_s)

    def stats(self) -> Dict[str, Any]:
        return self._t.call("stats", {}, timeout_s=self._timeout_s)

    def drain(self, timeout_s: float = 5.0) -> Dict[str, Any]:
        return self._t.call("drain", {"timeout_s": timeout_s},
                            timeout_s=timeout_s + 2.0)

    def quiesce(self) -> Dict[str, Any]:
        return self._t.call("quiesce", {},
                            timeout_s=self._timeout_s)

    def fence(self, reason: str = "forced") -> Dict[str, Any]:
        return self._t.call("fence", {"reason": reason},
                            timeout_s=self._timeout_s)

    def inject_partition(self, duration_s: float) -> Dict[str, Any]:
        return self._t.call("inject_partition",
                            {"duration_s": duration_s},
                            timeout_s=self._timeout_s)

    def telemetry(self, cursor: int = 0,
                  limit: int = 256) -> Dict[str, Any]:
        return self._t.call("telemetry",
                            {"cursor": cursor, "limit": limit},
                            timeout_s=self._timeout_s)

    def shutdown(self) -> Dict[str, Any]:
        return self._t.call("shutdown", {},
                            timeout_s=self._timeout_s)


def _tiny_engine_factory(flight_dir: Optional[str]):
    """The chaos harness's llama_tiny fp32 greedy engine, built
    identically in every agent process so completions are
    token-identical across hosts (and to the harness's reference)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.llama import Llama, llama_tiny
    from ray_tpu.serve.engine import LLMEngine

    cfg = llama_tiny(dtype=jnp.float32)
    model = Llama(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))

    def factory(generation: int) -> LLMEngine:
        eng = LLMEngine(model, params, max_slots=2, page_size=8,
                        n_pages=64, chunk=4, temperature=0.0,
                        seed=0, prefix_cache=True, eos_id=-1,
                        admit_timeout_s=0.25,
                        flight_dir=flight_dir)
        eng.start()
        # warm the jitted paths BEFORE the replica joins the fleet
        # (a cold first dispatch looks exactly like a wedge)
        eng.submit([3, 1, 4, 1, 5, 9, 2, 6],
                   max_new_tokens=4).result()
        eng.reset_latency_stats()
        return eng

    return factory


def main(argv: Optional[List[str]] = None) -> None:
    """Subprocess entry: ``python -m ray_tpu.serve.fleet.agent
    --replica-id r0 --directory-port N [--model fake|tiny]``. Prints
    ``READY <port>`` once registered and warm."""
    import argparse

    from ray_tpu.serve.fleet.transport import (SocketServer,
                                               SocketTransport)

    ap = argparse.ArgumentParser()
    ap.add_argument("--replica-id", required=True)
    ap.add_argument("--generation", type=int, default=0)
    ap.add_argument("--directory-host", default="127.0.0.1")
    ap.add_argument("--directory-port", type=int, default=None)
    ap.add_argument("--directory", action="append", default=None,
                    metavar="HOST:PORT",
                    help="ordered directory endpoint (repeatable: "
                         "primary first, then standbys; the agent "
                         "fails over client-side)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--model", choices=("fake", "tiny"),
                    default="fake")
    ap.add_argument("--token-delay-s", type=float, default=0.002)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--stall-deadline-s", type=float, default=None)
    ap.add_argument("--flight-dir", default=None)
    args = ap.parse_args(argv)

    if args.model == "fake":
        def factory(generation: int) -> ScriptedEngine:
            return ScriptedEngine(page_size=args.page_size,
                                  token_delay_s=args.token_delay_s)
    else:
        factory = _tiny_engine_factory(args.flight_dir)

    endpoints = []
    for spec in (args.directory or []):
        host, _, port = spec.rpartition(":")
        endpoints.append((host or "127.0.0.1", int(port)))
    if not endpoints:
        if args.directory_port is None:
            ap.error("need --directory or --directory-port")
        endpoints = [(args.directory_host, args.directory_port)]
    if len(endpoints) == 1:
        directory = DirectoryClient(SocketTransport(endpoints[0]))
    else:
        from ray_tpu.serve.fleet.replication import (
            FailoverDirectoryClient)
        directory = FailoverDirectoryClient(
            [SocketTransport(ep) for ep in endpoints])
    agent = ReplicaAgent(
        args.replica_id, factory, directory,
        generation=args.generation,
        stall_deadline_s=args.stall_deadline_s,
        flight_dir=args.flight_dir)
    server = SocketServer(agent.handle, host=args.host,
                          port=args.port, gate=agent.reachable)
    agent.addr = ["tcp", server.addr[0], server.addr[1]]
    agent.start()
    print(f"READY {server.addr[1]}", flush=True)
    try:
        while not agent._stop.is_set():
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        agent.shutdown()


if __name__ == "__main__":
    main()
