"""The fleet transport seam: loopback, sockets, injected faults.

Three implementations of one call surface,
``call(method, args, timeout_s=..., trace_id=...) -> result``:

- ``LoopbackTransport``: in-process, but HONEST — every request and
  response round-trips through the JSON wire encoding, and typed
  errors cross via ``wire.err``/``wire.raise_error`` exactly as they
  would over a socket. Tier-1 tests run the whole fleet on it.
- ``SocketTransport`` + ``SocketServer``: length-prefixed JSON over
  TCP (4-byte big-endian length, UTF-8 JSON payload), one connection
  per call, thread-per-connection server. Real process separation.
- ``FaultyTransport``: a seeded wrapper injecting drop / delay /
  duplicate / partition — the cross-process extension of
  ``serve/faults.py``'s in-engine fault plans.

Transport failures raise ``TransportError`` (``TransportTimeout``
for deadline cases) — NEVER a typed request error: the caller cannot
know whether the remote side executed the call, which is exactly the
ambiguity the router's suspect → directory-confirm → resubmit path
exists to resolve.
"""
from __future__ import annotations

import random
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu.serve.fleet import wire

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024
_max_frame = MAX_FRAME


def max_frame_bytes() -> int:
    """The fleet-wide frame ceiling every bulk payload must plan
    around: KV migration chunks size themselves to fit under it
    (serve/kv_migration.py) and telemetry scrapes bound their event
    windows by it — one explicit knob instead of two implicit ones."""
    return _max_frame


def set_max_frame_bytes(n: int) -> int:
    """Set the frame ceiling (tests shrink it to force the typed
    oversize rejection without building 64 MiB payloads). Returns the
    previous value so callers can restore it."""
    global _max_frame
    if int(n) < 1024:
        raise ValueError(f"max frame of {n} bytes is below the 1 KiB "
                         f"floor (control envelopes must always fit)")
    prev = _max_frame
    _max_frame = int(n)
    return prev

# handler(method, args, trace_id) -> JSON-serializable result
Handler = Callable[[str, Dict[str, Any], Optional[str]], Any]


class TransportError(RuntimeError):
    """The call may or may not have executed remotely."""


class TransportTimeout(TransportError):
    """No response within the per-call deadline."""


def send_frame(sock: socket.socket, payload: bytes) -> None:
    if len(payload) > _max_frame:
        raise TransportError(
            f"frame of {len(payload)} bytes exceeds the "
            f"{_max_frame}-byte max-frame knob")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_frame(sock: socket.socket) -> bytes:
    head = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(head)
    if n > _max_frame:
        raise TransportError(
            f"peer announced {n}-byte frame over the "
            f"{_max_frame}-byte max-frame knob")
    return _recv_exact(sock, n)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportError("connection closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _dispatch(handler: Handler, req: Dict[str, Any]
              ) -> Dict[str, Any]:
    """Run one decoded request envelope through a handler, catching
    typed errors into the wire error shape. Shared by the loopback
    transport and the socket server so both sides of the seam agree
    on what crosses it."""
    try:
        result = handler(req["method"], req.get("args") or {},
                         req.get("trace_id"))
        return wire.ok(result)
    except Exception as e:
        return wire.err(e)


class Transport:
    """Call surface every fleet component speaks."""

    def call(self, method: str, args: Dict[str, Any], *,
             timeout_s: Optional[float] = None,
             trace_id: Optional[str] = None) -> Any:
        raise NotImplementedError

    def close(self) -> None:
        pass


class LoopbackTransport(Transport):
    """In-process transport that still pays the wire toll: requests
    and responses are JSON-encoded and decoded, so anything that
    would not survive a socket does not survive loopback either."""

    def __init__(self, handler: Handler):
        self._handler = handler

    def call(self, method: str, args: Dict[str, Any], *,
             timeout_s: Optional[float] = None,
             trace_id: Optional[str] = None) -> Any:
        req = wire.decode(wire.encode(
            wire.request(method, args, trace_id)))
        resp = wire.decode(wire.encode(
            _dispatch(self._handler, req)))
        if not resp["ok"]:
            wire.raise_error(resp["error"])
        return resp["result"]


class SocketServer:
    """Thread-per-connection RPC server for one handler. ``addr`` is
    the bound ``(host, port)`` — pass port 0 to let the OS pick."""

    def __init__(self, handler: Handler, host: str = "127.0.0.1",
                 port: int = 0,
                 gate: Optional[Callable[[], bool]] = None):
        self._handler = handler
        # gate() -> False drops the connection WITHOUT responding —
        # the server-side half of a network partition (the client
        # sees a TransportError, never a typed refusal)
        self._gate = gate
        self._sock = socket.socket(socket.AF_INET,
                                   socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET,
                              socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.addr: Tuple[str, int] = self._sock.getsockname()
        self._stopped = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name=f"fleet-rpc-{self.addr[1]}", daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                while not self._stopped:
                    try:
                        payload = recv_frame(conn)
                    except TransportError:
                        return      # peer hung up
                    if self._gate is not None and not self._gate():
                        return      # partitioned: drop, no response
                    resp = _dispatch(self._handler,
                                     wire.decode(payload))
                    send_frame(conn, wire.encode(resp))
        except OSError:
            pass

    def stop(self) -> None:
        self._stopped = True
        try:
            self._sock.close()
        except OSError:
            pass


class SocketTransport(Transport):
    """Connection-per-call client for a ``SocketServer``. Stateless
    between calls, which keeps failure handling honest: any socket
    error is a ``TransportError`` and the next call starts clean."""

    def __init__(self, addr: Tuple[str, int], *,
                 connect_timeout_s: float = 2.0,
                 default_timeout_s: float = 10.0):
        self._addr = (addr[0], int(addr[1]))
        self._connect_timeout_s = connect_timeout_s
        self._default_timeout_s = default_timeout_s

    def call(self, method: str, args: Dict[str, Any], *,
             timeout_s: Optional[float] = None,
             trace_id: Optional[str] = None) -> Any:
        deadline = (timeout_s if timeout_s is not None
                    else self._default_timeout_s)
        try:
            sock = socket.create_connection(
                self._addr, timeout=min(self._connect_timeout_s,
                                        deadline))
        except socket.timeout as e:
            raise TransportTimeout(
                f"connect to {self._addr} timed out") from e
        except OSError as e:
            raise TransportError(
                f"connect to {self._addr} failed: {e}") from e
        try:
            with sock:
                sock.settimeout(deadline)
                send_frame(sock, wire.encode(
                    wire.request(method, args, trace_id)))
                resp = wire.decode(recv_frame(sock))
        except socket.timeout as e:
            raise TransportTimeout(
                f"{method} to {self._addr} timed out after "
                f"{deadline:.3f}s") from e
        except OSError as e:
            raise TransportError(
                f"{method} to {self._addr} failed: {e}") from e
        if not resp["ok"]:
            wire.raise_error(resp["error"])
        return resp["result"]


class FaultyTransport(Transport):
    """Seeded fault-injecting wrapper around any transport: the
    cross-process face of ``serve/faults.py``.

    - ``drop_p``: the call raises ``TransportError`` WITHOUT reaching
      the peer (request lost on the wire).
    - ``dup_p``: the call executes TWICE back-to-back and the second
      result is returned (duplicate delivery; receiver-side request
      keys and poll cursors must make this harmless).
    - ``delay_p`` / ``delay_s``: the call sleeps before executing.
    - ``partition()``: while partitioned, every call raises
      ``TransportError`` — the peer is unreachable both ways.
    - ``replay_last()``: re-deliver the most recent successful call
      verbatim, arbitrarily later — the DELAYED duplicate ``dup_p``
      can't model (back-to-back dups land inside one lease window;
      a held-then-replayed frame can straddle a renewal or even a
      re-registration boundary, which is exactly what fencing tokens
      exist to refuse).
    """

    def __init__(self, inner: Transport, *, seed: int = 0,
                 drop_p: float = 0.0, dup_p: float = 0.0,
                 delay_p: float = 0.0, delay_s: float = 0.01):
        self._inner = inner
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.drop_p = drop_p
        self.dup_p = dup_p
        self.delay_p = delay_p
        self.delay_s = delay_s
        self._partitioned_until: Optional[float] = None
        self._last: Optional[Tuple[str, Dict[str, Any]]] = None
        self.stats = {"calls": 0, "dropped": 0, "duplicated": 0,
                      "delayed": 0, "partitioned": 0, "replayed": 0}

    def partition(self, duration_s: Optional[float] = None) -> None:
        """Cut the link (for ``duration_s`` seconds, or until
        ``heal()``)."""
        with self._lock:
            self._partitioned_until = (
                float("inf") if duration_s is None
                else time.monotonic() + duration_s)

    def heal(self) -> None:
        with self._lock:
            self._partitioned_until = None

    def partitioned(self) -> bool:
        with self._lock:
            until = self._partitioned_until
        return until is not None and time.monotonic() < until

    def call(self, method: str, args: Dict[str, Any], *,
             timeout_s: Optional[float] = None,
             trace_id: Optional[str] = None) -> Any:
        with self._lock:
            self.stats["calls"] += 1
            drop = self._rng.random() < self.drop_p
            dup = self._rng.random() < self.dup_p
            delay = self._rng.random() < self.delay_p
        if self.partitioned():
            with self._lock:
                self.stats["partitioned"] += 1
            raise TransportError(
                f"partitioned: {method} undeliverable")
        if drop:
            with self._lock:
                self.stats["dropped"] += 1
            raise TransportError(f"injected drop of {method}")
        if delay:
            with self._lock:
                self.stats["delayed"] += 1
            time.sleep(self.delay_s)
        if dup:
            with self._lock:
                self.stats["duplicated"] += 1
            self._inner.call(method, args, timeout_s=timeout_s,
                             trace_id=trace_id)
        out = self._inner.call(method, args, timeout_s=timeout_s,
                               trace_id=trace_id)
        with self._lock:
            self._last = (method, dict(args))
        return out

    def replay_last(self, *, timeout_s: Optional[float] = None):
        """Re-deliver the last successful frame NOW (a duplicate the
        network held onto). Returns the peer's fresh answer — which,
        across a renewal/re-registration boundary, should be a typed
        fencing refusal, not a lease extension."""
        with self._lock:
            held = self._last
            self.stats["replayed"] += 1
        if held is None:
            raise TransportError("nothing to replay")
        method, args = held
        return self._inner.call(method, dict(args),
                                timeout_s=timeout_s)

    def close(self) -> None:
        self._inner.close()
