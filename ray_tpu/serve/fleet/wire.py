"""Fleet wire schema: JSON envelopes + typed errors across processes.

Every RPC is one request envelope and one response envelope, both
plain JSON objects (the transports own framing). The request carries
the ``trace_id`` so ``obs.request_phases()`` still reconstructs a
request end-to-end across the process boundary; the response carries
either a ``result`` or a typed ``error`` that ``raise_error``
rebuilds on the caller side BY NAME — the same convention
``errors.classify_http_status`` uses, so typing survives process
boundaries without pickling exceptions.

    request:  {"v": 1, "method": str, "args": {...},
               "trace_id": str | null}
    response: {"v": 1, "ok": true,  "result": ...}
            | {"v": 1, "ok": false,
               "error": {"type": str, "msg": str,
                         "retry_after_s": float | null}}

Fleet-specific typed errors subclass the serving taxonomy so the
HTTP proxy's status mapping keeps working unchanged:

- ``StaleFencingToken`` (-> EngineShutdown/503): a write carried a
  fencing token from a superseded generation. The writer is a
  zombie; it must re-register, never retry the write.
- ``UnknownMember`` (-> EngineShutdown/503): the directory has no
  such member — the canonical signal after a directory restart; the
  agent responds by re-registering (membership recovers from agent
  re-advertisement, not from directory persistence).
- ``AgentFenced`` (-> EngineDraining/503): the agent's lease lapsed
  and it self-fenced; it refuses admission until it re-registers
  under a new generation.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Optional

from ray_tpu.serve.errors import (DeadlineExceeded, EngineDraining,
                                  EngineOverloaded, EngineShutdown,
                                  PoolDegraded, RequestCancelled,
                                  RequestError, retry_after_s)

WIRE_VERSION = 1


class StaleFencingToken(EngineShutdown):
    """Write rejected: the fencing token belongs to a superseded
    registration. Monotonic tokens make this unambiguous — the writer
    lost a race it can never win again under that token."""


class UnknownMember(EngineShutdown):
    """The directory holds no member under that replica id (never
    registered, confirmed dead, or the directory restarted and lost
    its table). Agents re-register on seeing this."""


class AgentFenced(EngineDraining):
    """The agent's lease lapsed and it self-fenced: no admissions
    until it re-registers under a fresh generation."""


class NotPrimary(EngineShutdown):
    """The directory answering is a STANDBY: it replicates membership
    but does not adjudicate it. Callers holding an ordered endpoint
    list (``replication.FailoverDirectoryClient``) skip to the next
    endpoint; a standalone caller treats it like any 503."""


class KVPullAborted(EngineShutdown):
    """A cross-replica KV pull cannot complete on the donor side: the
    prefix is no longer resident, the transfer id is unknown (donor
    restarted or the transfer's pin deadline lapsed), or the donor is
    fenced/draining. TYPED so the requester distinguishes "donor
    said no" (abort the pull, fall back to plain prefill immediately)
    from a ``TransportError`` (donor may be alive; bounded retry
    first). Never retried: the donor's answer cannot improve under
    the same transfer."""


_WIRE_ERRORS = {
    cls.__name__: cls
    for cls in (RequestError, RequestCancelled, DeadlineExceeded,
                EngineOverloaded, EngineShutdown, EngineDraining,
                PoolDegraded, StaleFencingToken, UnknownMember,
                AgentFenced, NotPrimary, KVPullAborted)
}


class WireError(RuntimeError):
    """A remote failure with no typed equivalent on this side."""


def _error_class(name: str):
    cls = _WIRE_ERRORS.get(name)
    if cls is None and name == "ReplicaWedged":
        # lazy: watchdog imports engine_pool, which imports
        # fleet.routing — resolving at raise time keeps wire.py
        # import-order independent
        from ray_tpu.serve.watchdog import ReplicaWedged
        _WIRE_ERRORS[name] = cls = ReplicaWedged
    return cls


def request(method: str, args: Dict[str, Any],
            trace_id: Optional[str] = None) -> Dict[str, Any]:
    return {"v": WIRE_VERSION, "method": method, "args": args,
            "trace_id": trace_id}


def ok(result: Any) -> Dict[str, Any]:
    return {"v": WIRE_VERSION, "ok": True, "result": result}


def err(exc: BaseException) -> Dict[str, Any]:
    return {"v": WIRE_VERSION, "ok": False,
            "error": {"type": type(exc).__name__, "msg": str(exc),
                      "retry_after_s": retry_after_s(exc,
                                                     default=None)}}


def raise_error(error: Dict[str, Any]) -> None:
    """Rebuild and raise the typed error a response carried."""
    name = error.get("type", "WireError")
    msg = error.get("msg", "")
    cls = _error_class(name)
    if cls is None:
        raise WireError(f"{name}: {msg}")
    exc = cls(msg)
    ra = error.get("retry_after_s")
    if ra is not None:
        exc.retry_after_s = float(ra)
    raise exc


def encode(obj: Dict[str, Any]) -> bytes:
    return json.dumps(obj, separators=(",", ":"),
                      sort_keys=True).encode("utf-8")


def decode(data: bytes) -> Dict[str, Any]:
    return json.loads(data.decode("utf-8"))
