"""Crash-durable directory state: write-ahead log + snapshot.

The FleetDirectory's membership table is tiny but load-bearing: the
fencing-token high-water mark and the tombstone set are the two
pieces that must NEVER regress, even across a crash. This module
gives the directory the training side's torn-file discipline
(air/checkpoint.py) at control-plane scale:

- **WAL** (``wal.log``): one mutation per line, each line carrying a
  sha256 prefix over its own payload. Appends are flushed + fsynced
  before the mutating RPC answers, so an acknowledged register /
  tombstone / promotion survives SIGKILL. On recovery the log is
  scanned front to back; the FIRST record that fails its checksum
  (or json-decodes dirty, or lost its newline) marks the torn tail —
  everything from that byte on is TRUNCATED, never replayed. A torn
  record is a write the directory never acknowledged, so dropping it
  is the only correct reading.
- **Snapshot** (``snapshot.json``): periodic compaction. The payload
  is staged to a ``.tmp-`` file, checksummed (checksum line first,
  payload after — the same write-the-proof-last ordering as the
  checkpoint manifest), fsynced, and atomically renamed over the old
  snapshot; only then is the WAL truncated. A crash between those
  two steps replays WAL records that are already IN the snapshot —
  harmless, because every record type is idempotent under replay
  (membership upserts, tombstone maxes, fence-counter maxes).

Recovery = load snapshot (if its checksum verifies) + replay the
surviving WAL suffix. What does NOT survive is wall-time: leases are
stamped against the directory's monotonic clock, which resets with
the process, so the directory re-arms every recovered member with a
fresh full TTL instead of trusting a deadline from a dead clock.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.air.checkpoint import _fsync_dir

WAL_NAME = "wal.log"
SNAPSHOT_NAME = "snapshot.json"
_TMP_PREFIX = ".tmp-"


def _digest(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()[:16]


class DirectoryWAL:
    """Append-only mutation log + checksummed snapshot for one
    directory's durable state. Thread-safe; the directory calls
    ``append`` under its own lock anyway, but the WAL protects
    itself so recovery tooling can share an instance."""

    def __init__(self, data_dir: str, snapshot_every: int = 64):
        self.data_dir = data_dir
        self.snapshot_every = int(snapshot_every)
        os.makedirs(data_dir, exist_ok=True)
        self.wal_path = os.path.join(data_dir, WAL_NAME)
        self.snapshot_path = os.path.join(data_dir, SNAPSHOT_NAME)
        self._lock = threading.Lock()
        self._appends_since_snapshot = 0
        self.stats = {"appends": 0, "snapshots": 0,
                      "torn_records_truncated": 0,
                      "snapshot_checksum_rejects": 0}
        self._fh = None

    # ------------------------------------------------------------ write

    def _open(self):
        if self._fh is None:
            self._fh = open(self.wal_path, "ab")
        return self._fh

    def append(self, record: Dict[str, Any]) -> bool:
        """Durably append one mutation record. Returns True when the
        caller should compact (``snapshot_every`` appends since the
        last snapshot)."""
        payload = json.dumps(record, separators=(",", ":"),
                             sort_keys=True).encode("utf-8")
        line = _digest(payload).encode("ascii") + b" " + payload \
            + b"\n"
        with self._lock:
            fh = self._open()
            fh.write(line)
            fh.flush()
            os.fsync(fh.fileno())
            self.stats["appends"] += 1
            self._appends_since_snapshot += 1
            return self._appends_since_snapshot >= self.snapshot_every

    def snapshot(self, payload: Dict[str, Any]) -> None:
        """Atomically replace the snapshot with ``payload`` and
        truncate the WAL (in that order: a crash between the two
        replays snapshot-covered records, which replay is idempotent
        under)."""
        body = json.dumps(payload, separators=(",", ":"),
                          sort_keys=True).encode("utf-8")
        head = _digest(body).encode("ascii") + b"\n"
        with self._lock:
            stage = os.path.join(self.data_dir,
                                 _TMP_PREFIX + SNAPSHOT_NAME)
            with open(stage, "wb") as fh:
                fh.write(head + body)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(stage, self.snapshot_path)
            _fsync_dir(self.data_dir)
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            with open(self.wal_path, "wb") as fh:
                fh.flush()
                os.fsync(fh.fileno())
            _fsync_dir(self.data_dir)
            self._appends_since_snapshot = 0
            self.stats["snapshots"] += 1

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None

    # ------------------------------------------------------------- read

    def load(self) -> Tuple[Optional[Dict[str, Any]],
                            List[Dict[str, Any]]]:
        """Recover ``(snapshot_payload | None, wal_records)``. Detects
        and truncates a torn WAL tail in place; a snapshot that fails
        its checksum is ignored entirely (the WAL since the previous
        good snapshot was already truncated with it, so the directory
        falls back to agent re-advertisement — safe, just slower)."""
        snap = self._load_snapshot()
        records = self._load_wal()
        return snap, records

    def _load_snapshot(self) -> Optional[Dict[str, Any]]:
        if not os.path.exists(self.snapshot_path):
            return None
        with open(self.snapshot_path, "rb") as fh:
            head = fh.readline().strip()
            body = fh.read()
        try:
            if head.decode("ascii") != _digest(body):
                raise ValueError("checksum mismatch")
            return json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            self.stats["snapshot_checksum_rejects"] += 1
            return None

    def _load_wal(self) -> List[Dict[str, Any]]:
        if not os.path.exists(self.wal_path):
            return []
        records: List[Dict[str, Any]] = []
        good_end = 0
        torn = 0
        with open(self.wal_path, "rb") as fh:
            data = fh.read()
        offset = 0
        while offset < len(data):
            nl = data.find(b"\n", offset)
            if nl < 0:
                torn += 1          # no newline: write died mid-record
                break
            line = data[offset:nl]
            rec = self._parse_line(line)
            if rec is None:
                # checksum / shape failure: this record was never
                # acknowledged — truncate HERE and stop. Anything
                # after it rode a corrupted region and is equally
                # untrustworthy.
                torn += 1 + data.count(b"\n", nl + 1)
                break
            records.append(rec)
            good_end = nl + 1
            offset = nl + 1
        if torn:
            self.stats["torn_records_truncated"] += torn
            with self._lock:
                if self._fh is not None:
                    self._fh.close()
                    self._fh = None
                with open(self.wal_path, "r+b") as fh:
                    fh.truncate(good_end)
                    fh.flush()
                    os.fsync(fh.fileno())
        return records

    @staticmethod
    def _parse_line(line: bytes) -> Optional[Dict[str, Any]]:
        parts = line.split(b" ", 1)
        if len(parts) != 2:
            return None
        head, payload = parts
        try:
            if head.decode("ascii") != _digest(payload):
                return None
            rec = json.loads(payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return None
        return rec if isinstance(rec, dict) else None


def inject_torn_tail(data_dir: str,
                     garbage: bytes = b'f00dfeedcafe4bad {"op":"mem'
                     ) -> None:
    """Test/chaos hook: append a partial (torn) record to the WAL,
    simulating a crash mid-write. Recovery must truncate it."""
    path = os.path.join(data_dir, WAL_NAME)
    with open(path, "ab") as fh:
        fh.write(garbage)
        fh.flush()
        os.fsync(fh.fileno())


def wal_record_count(data_dir: str) -> int:
    """Count intact records currently in the WAL (diagnostic)."""
    path = os.path.join(data_dir, WAL_NAME)
    if not os.path.exists(path):
        return 0
    n = 0
    with open(path, "rb") as fh:
        for line in fh:
            if line.endswith(b"\n") and \
                    DirectoryWAL._parse_line(line[:-1]) is not None:
                n += 1
    return n
