"""FleetDirectory: lease-fenced membership for the serving fleet.

The control-plane half of the GCS split: a small service owning WHO
is in the fleet, nothing else. State per member, keyed by replica id:

- **generation** — the agent's incarnation counter (bumped every time
  the agent rebuilds its engine or re-registers after being fenced).
  A register from an OLDER generation than the directory has ever
  seen for that id is a zombie and is rejected.
- **fencing token** — strictly monotonic across the WHOLE directory
  (one counter), (re)issued at registration. Every subsequent write
  (renew, deregister) must quote it; a stale token is rejected typed
  ``StaleFencingToken``. Agents pass their last token back as
  ``min_fence`` when re-registering, so monotonicity survives even a
  directory that lost its table: the new directory's counter jumps
  past every token it ever issued.
- **lease** — liveness is a time-bounded claim, renewed by heartbeat.
  An expired lease makes the member a DEATH CANDIDATE; it is only
  removed when someone (the router) asks ``confirm_dead`` — the
  directory never guesses, and a late renewal before confirmation
  revives the lease (counted, for the curious).
- **advertisements** — each renewal piggybacks the agent's prefix
  digest and load report, which is what the router routes on.

Durability and availability are layered on without changing that
contract:

- ``data_dir=`` arms a **write-ahead log + snapshot** (``wal.py``):
  membership, generations, tombstones, and the fencing-token
  high-water mark are logged before the mutating RPC answers, so a
  crash-restarted directory recovers authoritative state immediately
  instead of waiting out a re-advertisement window. Torn WAL tails
  are truncated, never replayed. Leases are re-armed with a full TTL
  at recovery — monotonic clocks don't survive the process, so a
  deadline stamped by the dead incarnation proves nothing.
- ``role="standby"`` makes this directory a **hot standby**: it
  applies replicated deltas (``rpc_repl_apply`` / ``rpc_repl_sync``)
  but answers every adjudicating RPC — register, renew, deregister,
  confirm_dead, snapshot — with typed ``NotPrimary`` so two
  directories can never both arbitrate. ``rpc_promote`` flips it to
  primary with an epoch bump FOLDED INTO the fence counter
  (``+ FENCE_EPOCH_STRIDE``): even if the dying primary issued
  tokens the standby never saw replicated, no token the new primary
  issues can regress below them.
"""
from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.serve.fleet.transport import Transport
from ray_tpu.serve.fleet.wire import (NotPrimary, StaleFencingToken,
                                      UnknownMember)

# Fence-counter jump applied at standby promotion: an upper bound on
# the tokens an async-replicating primary could have issued without
# the deltas reaching the standby before it died. Registers replicate
# one delta each, so the true gap is the replication queue depth;
# 1024 documents "a lot of margin" without threatening the int.
FENCE_EPOCH_STRIDE = 1024

PRIMARY = "primary"
STANDBY = "standby"


class _Member:
    __slots__ = ("replica_id", "addr", "generation", "fence",
                 "lease_expires", "digest", "load", "page_size",
                 "wedged", "registered_at", "role")

    def __init__(self, replica_id: str, addr: List[Any],
                 generation: int, fence: int, lease_expires: float,
                 page_size: int, registered_at: float,
                 role: str = "unified"):
        self.replica_id = replica_id
        self.addr = addr
        self.generation = generation
        self.fence = fence
        self.lease_expires = lease_expires
        self.digest: List[int] = []
        self.load: Dict[str, Any] = {}
        self.page_size = page_size
        self.wedged = False
        self.registered_at = registered_at
        # scheduling role ("prefill"/"decode"/"unified") — unrelated
        # to the directory's own PRIMARY/STANDBY role
        self.role = role


class FleetDirectory:
    """Membership table + fencing authority. Thread-safe; exposes
    ``handle`` as the transport handler."""

    def __init__(self, lease_ttl_s: float = 1.0,
                 time_fn=time.monotonic, *,
                 data_dir: Optional[str] = None,
                 snapshot_every: int = 64,
                 role: str = PRIMARY,
                 replicator=None):
        self.lease_ttl_s = float(lease_ttl_s)
        self._now = time_fn
        self._lock = threading.Lock()
        self._members: Dict[str, _Member] = {}
        # Global prefix directory: page-path-hash -> replica ids
        # currently advertising that hash in their digest. Soft
        # state, repainted by every renewal and dropped with the
        # member row (deregister, reap, supersession) — which IS the
        # generation fence: a dead incarnation's holdings can never
        # outlive its lease, so the router never dials a donor for
        # pages a newer incarnation no longer holds.
        self._prefix_index: Dict[int, set] = {}
        # replica_id -> highest generation ever confirmed dead or
        # retired; zombie registrations at or below it are rejected
        self._tombstones: Dict[str, int] = {}
        self._fence_counter = 0
        self.role = role
        self.epoch = 0
        self._repl_last_seq = 0
        self._replicator = replicator
        self.events: collections.deque = collections.deque(
            maxlen=4096)
        # monotone per-process event counter: the telemetry scrape
        # cursors over it exactly like an EventLog seq
        self._event_seq = 0
        self.counters = {"registers": 0, "renews": 0,
                         "stale_fence_rejects": 0,
                         "unknown_member_rejects": 0,
                         "zombie_register_rejects": 0,
                         "late_renewals": 0, "confirmed_dead": 0,
                         "deregisters": 0, "wedges_reported": 0,
                         "not_primary_rejects": 0,
                         "recovered_members": 0,
                         "wal_torn_truncated": 0,
                         "repl_applied": 0, "repl_syncs": 0,
                         "repl_gaps": 0,
                         "repl_stale_epoch_rejects": 0,
                         "promotions": 0,
                         "prefix_queries": 0, "prefix_hits": 0}
        self._wal = None
        if data_dir is not None:
            from ray_tpu.serve.fleet.wal import DirectoryWAL
            self._wal = DirectoryWAL(data_dir,
                                     snapshot_every=snapshot_every)
            self._recover()

    # ------------------------------------------------- durable state

    def _event(self, kind: str, **fields) -> None:
        ev = {"seq": self._event_seq, "t": round(self._now(), 4),
              "kind": kind, "epoch": self.epoch}
        self._event_seq += 1
        ev.update(fields)
        self.events.append(ev)

    def _durable_payload(self) -> Dict[str, Any]:
        return {
            "members": [{"replica_id": m.replica_id, "addr": m.addr,
                         "generation": m.generation,
                         "fence": m.fence,
                         "page_size": m.page_size,
                         "role": m.role}
                        for m in self._members.values()],
            "tombstones": dict(self._tombstones),
            "fence_counter": self._fence_counter,
            "epoch": self.epoch,
            "role": self.role,
        }

    def _persist(self, record: Dict[str, Any]) -> None:
        if self._wal is None:
            return
        if self._wal.append(record):
            self._wal.snapshot(self._durable_payload())

    def _replicate(self, record: Dict[str, Any]) -> None:
        if self._replicator is not None and self.role == PRIMARY:
            self._replicator.publish(self.epoch, record)

    def _apply_record(self, rec: Dict[str, Any],
                      now: float) -> None:
        """Apply one WAL/replication record (idempotent under
        replay). Caller holds the lock."""
        op = rec.get("op")
        if op == "member":
            rid = rec["replica_id"]
            fence = int(rec["fence"])
            self._drop_prefix_holdings(self._members.get(rid))
            self._members[rid] = _Member(
                rid, list(rec["addr"]), int(rec["generation"]),
                fence, now + self.lease_ttl_s,
                int(rec.get("page_size", 0)), now,
                role=rec.get("role", "unified"))
            self._fence_counter = max(self._fence_counter, fence)
        elif op == "tombstone":
            rid = rec["replica_id"]
            gen = int(rec["generation"])
            self._tombstones[rid] = max(
                self._tombstones.get(rid, -1), gen)
            m = self._members.get(rid)
            if m is not None and m.generation <= gen:
                self._drop_prefix_holdings(m)
                del self._members[rid]
        elif op == "promote":
            self.epoch = max(self.epoch, int(rec["epoch"]))
            self._fence_counter = max(self._fence_counter,
                                      int(rec["fence_counter"]))
            self.role = rec.get("role", self.role)

    def _recover(self) -> None:
        snap, records = self._wal.load()
        now = self._now()
        with self._lock:
            if snap is not None:
                for row in snap.get("members", ()):
                    self._apply_record(dict(row, op="member"), now)
                for rid, gen in (snap.get("tombstones") or
                                 {}).items():
                    self._tombstones[rid] = max(
                        self._tombstones.get(rid, -1), int(gen))
                self._fence_counter = max(
                    self._fence_counter,
                    int(snap.get("fence_counter", 0)))
                self.epoch = max(self.epoch,
                                 int(snap.get("epoch", 0)))
                self.role = snap.get("role", self.role)
            for rec in records:
                self._apply_record(rec, now)
            # tombstones beat membership whatever order they landed
            for rid, gen in self._tombstones.items():
                m = self._members.get(rid)
                if m is not None and m.generation <= gen:
                    del self._members[rid]
            self.counters["recovered_members"] = len(self._members)
            self.counters["wal_torn_truncated"] = \
                self._wal.stats["torn_records_truncated"]
            if self._members or snap is not None or records:
                self._event("recover",
                            members=len(self._members),
                            fence_counter=self._fence_counter,
                            torn_truncated=self.counters[
                                "wal_torn_truncated"])

    # --------------------------------------------- prefix directory

    def _repaint_prefix_index(self, m: _Member,
                              digest: List[int]) -> None:
        """Replace ``m``'s advertised holdings with ``digest``.
        Caller holds the lock."""
        new = {int(h) for h in digest}
        old = set(m.digest)
        for h in old - new:
            holders = self._prefix_index.get(h)
            if holders is not None:
                holders.discard(m.replica_id)
                if not holders:
                    del self._prefix_index[h]
        for h in new - old:
            self._prefix_index.setdefault(h, set()).add(
                m.replica_id)
        m.digest = sorted(new)

    def _drop_prefix_holdings(self, m: Optional[_Member]) -> None:
        """Tombstone a member's holdings with its membership row.
        Caller holds the lock."""
        if m is None:
            return
        self._repaint_prefix_index(m, [])

    def _require_primary(self, op: str) -> None:
        if self.role != PRIMARY:
            self.counters["not_primary_rejects"] += 1
            raise NotPrimary(
                f"{op} refused: this directory is a standby "
                f"(epoch {self.epoch}); ask the primary")

    # ----------------------------------------------------- RPC surface

    def handle(self, method: str, args: Dict[str, Any],
               trace_id: Optional[str] = None) -> Any:
        fn = getattr(self, "rpc_" + method, None)
        if fn is None:
            raise UnknownMember(f"directory has no method {method}")
        return fn(**args)

    def rpc_ping(self) -> Dict[str, Any]:
        return {"ok": True, "members": len(self._members),
                "role": self.role, "epoch": self.epoch}

    def rpc_register(self, replica_id: str, addr: List[Any],
                     generation: int, page_size: int = 0,
                     min_fence: int = 0,
                     role: str = "unified") -> Dict[str, Any]:
        with self._lock:
            self._require_primary("register")
            if role not in ("prefill", "decode", "unified"):
                raise ValueError(
                    f"unknown replica role {role!r}; expected "
                    f"prefill/decode/unified")
            tomb = self._tombstones.get(replica_id)
            if tomb is not None and generation <= tomb:
                self.counters["zombie_register_rejects"] += 1
                raise StaleFencingToken(
                    f"register of {replica_id} gen {generation} "
                    f"rejected: generation <= {tomb} was already "
                    f"confirmed dead")
            cur = self._members.get(replica_id)
            if cur is not None and generation < cur.generation:
                self.counters["zombie_register_rejects"] += 1
                raise StaleFencingToken(
                    f"register of {replica_id} gen {generation} "
                    f"rejected: gen {cur.generation} is current")
            self._fence_counter = max(self._fence_counter,
                                      int(min_fence)) + 1
            fence = self._fence_counter
            now = self._now()
            self._drop_prefix_holdings(cur)
            self._members[replica_id] = _Member(
                replica_id, list(addr), int(generation), fence,
                now + self.lease_ttl_s, int(page_size), now,
                role=role)
            self.counters["registers"] += 1
            rec = {"op": "member", "replica_id": replica_id,
                   "addr": list(addr), "generation": int(generation),
                   "fence": fence, "page_size": int(page_size),
                   "role": role}
            self._persist(rec)
            self._replicate(rec)
            self._event("fence_issued", replica_id=replica_id,
                        generation=int(generation), fence=fence)
            return {"fence": fence, "generation": int(generation),
                    "lease_ttl_s": self.lease_ttl_s}

    def rpc_renew(self, replica_id: str, fence: int,
                  digest: Optional[List[int]] = None,
                  load: Optional[Dict[str, Any]] = None,
                  wedged: bool = False) -> Dict[str, Any]:
        with self._lock:
            self._require_primary("renew")
            m = self._members.get(replica_id)
            if m is None:
                self.counters["unknown_member_rejects"] += 1
                raise UnknownMember(
                    f"renew from unregistered {replica_id} (directory "
                    f"restart or confirmed death); re-register")
            if int(fence) != m.fence:
                self.counters["stale_fence_rejects"] += 1
                raise StaleFencingToken(
                    f"renew of {replica_id} with fence {fence} "
                    f"rejected: current fence is {m.fence}")
            now = self._now()
            if now > m.lease_expires:
                self.counters["late_renewals"] += 1
            m.lease_expires = now + self.lease_ttl_s
            if digest is not None:
                self._repaint_prefix_index(m, list(digest))
            if load is not None:
                m.load = dict(load)
            if wedged and not m.wedged:
                self.counters["wedges_reported"] += 1
            m.wedged = bool(wedged)
            self.counters["renews"] += 1
            # renewals are NOT persisted: leases are re-armed fresh at
            # recovery (a dead clock's deadline proves nothing), and
            # digest/load are soft state the next renewal repaints
            return {"lease_ttl_s": self.lease_ttl_s}

    def rpc_deregister(self, replica_id: str,
                       fence: int) -> Dict[str, Any]:
        with self._lock:
            self._require_primary("deregister")
            m = self._members.get(replica_id)
            if m is None:
                raise UnknownMember(f"{replica_id} not registered")
            if int(fence) != m.fence:
                self.counters["stale_fence_rejects"] += 1
                raise StaleFencingToken(
                    f"deregister of {replica_id} with fence {fence} "
                    f"rejected: current fence is {m.fence}")
            self._drop_prefix_holdings(m)
            del self._members[replica_id]
            self._tombstones[replica_id] = max(
                self._tombstones.get(replica_id, -1), m.generation)
            self.counters["deregisters"] += 1
            rec = {"op": "tombstone", "replica_id": replica_id,
                   "generation": m.generation}
            self._persist(rec)
            self._replicate(rec)
            return {"ok": True}

    def rpc_confirm_dead(self, replica_id: str,
                         fence: int) -> Dict[str, Any]:
        """Adjudicate a router's suspicion. Dead means: unknown id,
        a superseded fence (the incarnation the router talked to is
        gone), or an expired lease (which this call then reaps). A
        member with a live lease is NOT dead, however the transport
        to it looked from the router's side."""
        with self._lock:
            self._require_primary("confirm_dead")
            m = self._members.get(replica_id)
            if m is None:
                return {"dead": True, "reason": "unknown"}
            if int(fence) != m.fence:
                return {"dead": True, "reason": "superseded",
                        "current_fence": m.fence}
            now = self._now()
            if now <= m.lease_expires:
                return {"dead": False,
                        "lease_remaining_s":
                            m.lease_expires - now}
            self._drop_prefix_holdings(m)
            del self._members[replica_id]
            self._tombstones[replica_id] = max(
                self._tombstones.get(replica_id, -1), m.generation)
            self.counters["confirmed_dead"] += 1
            rec = {"op": "tombstone", "replica_id": replica_id,
                   "generation": m.generation}
            self._persist(rec)
            self._replicate(rec)
            return {"dead": True, "reason": "lease_expired",
                    "expired_for_s": now - m.lease_expires}

    def rpc_snapshot(self) -> Dict[str, Any]:
        with self._lock:
            # routing reads are adjudication too: a standby's view
            # may be behind the primary's, so it refuses rather than
            # serving stale authority
            self._require_primary("snapshot")
            now = self._now()
            members = [{
                "replica_id": m.replica_id, "addr": m.addr,
                "generation": m.generation, "fence": m.fence,
                "lease_remaining_s": m.lease_expires - now,
                "expired": now > m.lease_expires,
                "wedged": m.wedged, "digest": m.digest,
                "load": m.load, "page_size": m.page_size,
                "role": m.role,
            } for m in self._members.values()]
            return {"members": members,
                    "fence_counter": self._fence_counter,
                    "lease_ttl_s": self.lease_ttl_s,
                    "epoch": self.epoch}

    def rpc_prefix_holders(self, hashes: List[int],
                           limit: int = 4) -> Dict[str, Any]:
        """Who can donate this prefix? ``hashes`` is the requester's
        rolling page-path-hash chain (prefix_cache.path_hashes order
        — hash k covers pages 0..k). Holders are ranked by matched
        CONTIGUOUS prefix length, longest donor first; members with
        lapsed leases or a reported wedge never appear, however
        recently they advertised. Primary-only, same staleness
        argument as ``snapshot``."""
        with self._lock:
            self._require_primary("prefix_holders")
            self.counters["prefix_queries"] += 1
            chain = [int(h) for h in hashes]
            out: List[Dict[str, Any]] = []
            if chain:
                now = self._now()
                for rid in self._prefix_index.get(chain[0], ()):
                    m = self._members.get(rid)
                    if (m is None or now > m.lease_expires
                            or m.wedged):
                        continue
                    n = 0
                    for h in chain:
                        if rid not in self._prefix_index.get(h, ()):
                            break
                        n += 1
                    out.append({"replica_id": rid,
                                "generation": m.generation,
                                "fence": m.fence,
                                "addr": list(m.addr),
                                "n_matched": n})
                out.sort(key=lambda r: (-r["n_matched"],
                                        r["replica_id"]))
                out = out[:max(1, int(limit))]
            if out:
                self.counters["prefix_hits"] += 1
            return {"holders": out}

    def rpc_stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {"members": len(self._members),
                   "prefix_index_hashes": len(self._prefix_index),
                   "fence_counter": self._fence_counter,
                   "tombstones": dict(self._tombstones),
                   "counters": dict(self.counters),
                   "role": self.role, "epoch": self.epoch}
            if self._wal is not None:
                out["wal"] = dict(self._wal.stats)
            return out

    def rpc_events(self) -> Dict[str, Any]:
        with self._lock:
            return {"events": list(self.events)}

    def rpc_role(self) -> Dict[str, Any]:
        with self._lock:
            return {"role": self.role, "epoch": self.epoch,
                    "fence_counter": self._fence_counter,
                    "members": len(self._members)}

    def rpc_telemetry(self, cursor: int = 0,
                      limit: int = 256) -> Dict[str, Any]:
        """The fleet scrape seam, control-plane side. Served by
        primaries AND standbys (no ``_require_primary`` — an
        operator needs the standby's view during a failover most of
        all). The directory's dict events are rendered in the common
        telemetry event shape (seq/t/type/rid/data) so the collector
        merges them with agent/router EventLog streams untranslated.
        """
        from ray_tpu.util import metrics
        cursor = max(0, int(cursor))
        limit = max(1, int(limit))
        with self._lock:
            evs = list(self.events)
            total = self._event_seq
            role = self.role
            epoch = self.epoch
            fence = self._fence_counter
        oldest = evs[0]["seq"] if evs else total
        dropped = max(0, oldest - cursor)
        window = [e for e in evs if e["seq"] >= cursor][:limit]
        next_cursor = (window[-1]["seq"] + 1) if window \
            else max(cursor, total)
        events = [{"seq": e["seq"], "t": e["t"], "type": e["kind"],
                   "rid": e.get("replica_id"), "sid": None,
                   "data": {k: v for k, v in e.items()
                            if k not in ("seq", "t", "kind",
                                         "replica_id")}}
                  for e in window]
        return {
            "role": "directory",
            "replica_id": f"directory-{role}",
            "generation": epoch,
            "fence": fence,
            "state": role,
            "pid": os.getpid(),
            "clock": {"mono": time.monotonic(),
                      "wall": time.time()},
            "metrics_text": metrics.prometheus_text(),
            "events": events,
            "cursor": next_cursor,
            "events_total": total,
            "dropped": dropped,
        }

    # ------------------------------------------------- replication

    def rpc_repl_sync(self, epoch: int, seq: int,
                      state: Dict[str, Any]) -> Dict[str, Any]:
        """Full-state bootstrap from the primary. Replaces the
        standby's membership view wholesale (the primary's table IS
        the truth while it lives)."""
        with self._lock:
            if self.role == PRIMARY or int(epoch) < self.epoch:
                self.counters["repl_stale_epoch_rejects"] += 1
                raise StaleFencingToken(
                    f"repl_sync at epoch {epoch} rejected: this "
                    f"directory is {self.role} at epoch "
                    f"{self.epoch}")
            now = self._now()
            self._members.clear()
            self._prefix_index.clear()
            for row in state.get("members", ()):
                self._apply_record(dict(row, op="member"), now)
            for rid, gen in (state.get("tombstones")
                             or {}).items():
                self._tombstones[rid] = max(
                    self._tombstones.get(rid, -1), int(gen))
            self._fence_counter = max(
                self._fence_counter,
                int(state.get("fence_counter", 0)))
            self.epoch = max(self.epoch, int(epoch))
            self._repl_last_seq = int(seq)
            self.counters["repl_syncs"] += 1
            if self._wal is not None:
                self._wal.snapshot(self._durable_payload())
            self._event("repl_sync",
                        members=len(self._members),
                        fence_counter=self._fence_counter)
            return {"ok": True, "members": len(self._members)}

    def rpc_repl_apply(self, epoch: int, seq: int,
                       record: Dict[str, Any]) -> Dict[str, Any]:
        """Apply one membership delta streamed by the primary."""
        with self._lock:
            if self.role == PRIMARY or int(epoch) < self.epoch:
                self.counters["repl_stale_epoch_rejects"] += 1
                raise StaleFencingToken(
                    f"repl_apply at epoch {epoch} rejected: this "
                    f"directory is {self.role} at epoch "
                    f"{self.epoch}")
            if int(seq) != self._repl_last_seq + 1:
                self.counters["repl_gaps"] += 1
            self._repl_last_seq = int(seq)
            self._apply_record(record, self._now())
            self._persist(record)
            self.counters["repl_applied"] += 1
            if record.get("op") == "member":
                self._event("repl_member",
                            replica_id=record.get("replica_id"),
                            fence=int(record.get("fence", 0)))
            return {"ok": True, "seq": self._repl_last_seq}

    def rpc_promote(self, reason: str = "",
                    min_fence: int = 0) -> Dict[str, Any]:
        """Flip a standby to primary. The epoch bump is FOLDED INTO
        the fence counter: the new primary's first token clears every
        token the old primary could have issued unreplicated, so no
        fencing token ever regresses across failover. Idempotent —
        promoting a primary is a no-op answer, not an error."""
        with self._lock:
            if self.role == PRIMARY:
                return {"promoted": False, "role": self.role,
                        "epoch": self.epoch,
                        "fence_counter": self._fence_counter}
            fence_before = self._fence_counter
            self.epoch += 1
            self._fence_counter = max(self._fence_counter,
                                      int(min_fence)) \
                + FENCE_EPOCH_STRIDE
            self.role = PRIMARY
            now = self._now()
            for m in self._members.values():
                # replicated members get a fresh full lease: their
                # agents have been renewing against the DEAD primary
                # and deserve a whole TTL to find this one
                m.lease_expires = now + self.lease_ttl_s
            self.counters["promotions"] += 1
            rec = {"op": "promote", "epoch": self.epoch,
                   "fence_counter": self._fence_counter,
                   "role": PRIMARY}
            self._persist(rec)
            if self._wal is not None:
                self._wal.snapshot(self._durable_payload())
            self._event("promote", reason=reason,
                        fence_before=fence_before,
                        fence_after=self._fence_counter,
                        members=len(self._members))
            return {"promoted": True, "role": self.role,
                    "epoch": self.epoch,
                    "fence_counter": self._fence_counter,
                    "fence_before": fence_before,
                    "members": len(self._members)}


class DirectoryClient:
    """Typed client wrapper over any transport to a directory."""

    def __init__(self, transport: Transport,
                 timeout_s: float = 2.0):
        self._t = transport
        self._timeout_s = timeout_s

    def ping(self) -> Dict[str, Any]:
        return self._t.call("ping", {}, timeout_s=self._timeout_s)

    def register(self, replica_id: str, addr: List[Any],
                 generation: int, page_size: int = 0,
                 min_fence: int = 0,
                 role: str = "unified") -> Dict[str, Any]:
        return self._t.call(
            "register",
            {"replica_id": replica_id, "addr": addr,
             "generation": generation, "page_size": page_size,
             "min_fence": min_fence, "role": role},
            timeout_s=self._timeout_s)

    def renew(self, replica_id: str, fence: int,
              digest: Optional[List[int]] = None,
              load: Optional[Dict[str, Any]] = None,
              wedged: bool = False) -> Dict[str, Any]:
        return self._t.call(
            "renew",
            {"replica_id": replica_id, "fence": fence,
             "digest": digest, "load": load, "wedged": wedged},
            timeout_s=self._timeout_s)

    def deregister(self, replica_id: str,
                   fence: int) -> Dict[str, Any]:
        return self._t.call(
            "deregister",
            {"replica_id": replica_id, "fence": fence},
            timeout_s=self._timeout_s)

    def confirm_dead(self, replica_id: str,
                     fence: int) -> Dict[str, Any]:
        return self._t.call(
            "confirm_dead",
            {"replica_id": replica_id, "fence": fence},
            timeout_s=self._timeout_s)

    def snapshot(self) -> Dict[str, Any]:
        return self._t.call("snapshot", {},
                            timeout_s=self._timeout_s)

    def prefix_holders(self, hashes: List[int],
                       limit: int = 4) -> Dict[str, Any]:
        return self._t.call(
            "prefix_holders",
            {"hashes": list(hashes), "limit": limit},
            timeout_s=self._timeout_s)

    def stats(self) -> Dict[str, Any]:
        return self._t.call("stats", {}, timeout_s=self._timeout_s)

    def events(self) -> Dict[str, Any]:
        return self._t.call("events", {}, timeout_s=self._timeout_s)

    def role(self) -> Dict[str, Any]:
        return self._t.call("role", {}, timeout_s=self._timeout_s)

    def telemetry(self, cursor: int = 0,
                  limit: int = 256) -> Dict[str, Any]:
        return self._t.call("telemetry",
                            {"cursor": cursor, "limit": limit},
                            timeout_s=self._timeout_s)

    def promote(self, reason: str = "",
                min_fence: int = 0) -> Dict[str, Any]:
        return self._t.call(
            "promote", {"reason": reason, "min_fence": min_fence},
            timeout_s=self._timeout_s)


def main(argv: Optional[List[str]] = None) -> None:
    """Subprocess entry: ``python -m ray_tpu.serve.fleet.directory
    --port N [--data-dir D] [--role standby --peer H:P
    --promote-after-s S] [--standby H:P]``. Prints ``READY <port>``
    once listening."""
    import argparse

    from ray_tpu.serve.fleet.transport import (SocketServer,
                                               SocketTransport)

    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--lease-ttl-s", type=float, default=1.0)
    ap.add_argument("--data-dir", default=None,
                    help="arm WAL+snapshot durability here")
    ap.add_argument("--snapshot-every", type=int, default=64)
    ap.add_argument("--role", choices=(PRIMARY, STANDBY),
                    default=PRIMARY)
    ap.add_argument("--standby", action="append", default=[],
                    help="host:port of a standby to replicate to "
                         "(primary side; repeatable)")
    ap.add_argument("--peer", default=None,
                    help="host:port of the primary to monitor "
                         "(standby side)")
    ap.add_argument("--promote-after-s", type=float, default=3.0)
    args = ap.parse_args(argv)

    def _hp(s: str):
        host, _, port = s.rpartition(":")
        return host or "127.0.0.1", int(port)

    replicator = None
    if args.standby:
        from ray_tpu.serve.fleet.replication import Replicator
        replicator = Replicator(
            [SocketTransport(_hp(s)) for s in args.standby])
    directory = FleetDirectory(lease_ttl_s=args.lease_ttl_s,
                               data_dir=args.data_dir,
                               snapshot_every=args.snapshot_every,
                               role=args.role,
                               replicator=replicator)
    if replicator is not None:
        replicator.attach(directory)
        replicator.start()
    monitor = None
    if args.role == STANDBY and args.peer:
        from ray_tpu.serve.fleet.replication import StandbyMonitor
        monitor = StandbyMonitor(
            directory, SocketTransport(_hp(args.peer)),
            promote_after_s=args.promote_after_s).start()
    server = SocketServer(directory.handle, host=args.host,
                          port=args.port)
    print(f"READY {server.addr[1]}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        if monitor is not None:
            monitor.stop()
        if replicator is not None:
            replicator.stop()
        server.stop()


if __name__ == "__main__":
    main()
