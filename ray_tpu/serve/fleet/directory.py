"""FleetDirectory: lease-fenced membership for the serving fleet.

The control-plane half of the GCS split: a small service owning WHO
is in the fleet, nothing else. State per member, keyed by replica id:

- **generation** — the agent's incarnation counter (bumped every time
  the agent rebuilds its engine or re-registers after being fenced).
  A register from an OLDER generation than the directory has ever
  seen for that id is a zombie and is rejected.
- **fencing token** — strictly monotonic across the WHOLE directory
  (one counter), (re)issued at registration. Every subsequent write
  (renew, deregister) must quote it; a stale token is rejected typed
  ``StaleFencingToken``. Agents pass their last token back as
  ``min_fence`` when re-registering, so monotonicity survives a
  directory crash/restart even though the table does not: the new
  directory's counter jumps past every token it ever issued.
- **lease** — liveness is a time-bounded claim, renewed by heartbeat.
  An expired lease makes the member a DEATH CANDIDATE; it is only
  removed when someone (the router) asks ``confirm_dead`` — the
  directory never guesses, and a late renewal before confirmation
  revives the lease (counted, for the curious).
- **advertisements** — each renewal piggybacks the agent's prefix
  digest and load report, which is what the router routes on.

The directory holds NO request state and NO engine state, which is
why crash/restart is cheap: agents notice ``UnknownMember`` on their
next renewal and re-register, and the membership table rebuilds
itself from the fleet within one lease period.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.serve.fleet.transport import Transport
from ray_tpu.serve.fleet.wire import (StaleFencingToken,
                                      UnknownMember)


class _Member:
    __slots__ = ("replica_id", "addr", "generation", "fence",
                 "lease_expires", "digest", "load", "page_size",
                 "wedged", "registered_at")

    def __init__(self, replica_id: str, addr: List[Any],
                 generation: int, fence: int, lease_expires: float,
                 page_size: int, registered_at: float):
        self.replica_id = replica_id
        self.addr = addr
        self.generation = generation
        self.fence = fence
        self.lease_expires = lease_expires
        self.digest: List[int] = []
        self.load: Dict[str, Any] = {}
        self.page_size = page_size
        self.wedged = False
        self.registered_at = registered_at


class FleetDirectory:
    """Membership table + fencing authority. Thread-safe; exposes
    ``handle`` as the transport handler."""

    def __init__(self, lease_ttl_s: float = 1.0,
                 time_fn=time.monotonic):
        self.lease_ttl_s = float(lease_ttl_s)
        self._now = time_fn
        self._lock = threading.Lock()
        self._members: Dict[str, _Member] = {}
        # replica_id -> highest generation ever confirmed dead or
        # retired; zombie registrations at or below it are rejected
        self._tombstones: Dict[str, int] = {}
        self._fence_counter = 0
        self.counters = {"registers": 0, "renews": 0,
                         "stale_fence_rejects": 0,
                         "unknown_member_rejects": 0,
                         "zombie_register_rejects": 0,
                         "late_renewals": 0, "confirmed_dead": 0,
                         "deregisters": 0, "wedges_reported": 0}

    # ----------------------------------------------------- RPC surface

    def handle(self, method: str, args: Dict[str, Any],
               trace_id: Optional[str] = None) -> Any:
        fn = getattr(self, "rpc_" + method, None)
        if fn is None:
            raise UnknownMember(f"directory has no method {method}")
        return fn(**args)

    def rpc_ping(self) -> Dict[str, Any]:
        return {"ok": True, "members": len(self._members)}

    def rpc_register(self, replica_id: str, addr: List[Any],
                     generation: int, page_size: int = 0,
                     min_fence: int = 0) -> Dict[str, Any]:
        with self._lock:
            tomb = self._tombstones.get(replica_id)
            if tomb is not None and generation <= tomb:
                self.counters["zombie_register_rejects"] += 1
                raise StaleFencingToken(
                    f"register of {replica_id} gen {generation} "
                    f"rejected: generation <= {tomb} was already "
                    f"confirmed dead")
            cur = self._members.get(replica_id)
            if cur is not None and generation < cur.generation:
                self.counters["zombie_register_rejects"] += 1
                raise StaleFencingToken(
                    f"register of {replica_id} gen {generation} "
                    f"rejected: gen {cur.generation} is current")
            self._fence_counter = max(self._fence_counter,
                                      int(min_fence)) + 1
            fence = self._fence_counter
            now = self._now()
            self._members[replica_id] = _Member(
                replica_id, list(addr), int(generation), fence,
                now + self.lease_ttl_s, int(page_size), now)
            self.counters["registers"] += 1
            return {"fence": fence, "generation": int(generation),
                    "lease_ttl_s": self.lease_ttl_s}

    def rpc_renew(self, replica_id: str, fence: int,
                  digest: Optional[List[int]] = None,
                  load: Optional[Dict[str, Any]] = None,
                  wedged: bool = False) -> Dict[str, Any]:
        with self._lock:
            m = self._members.get(replica_id)
            if m is None:
                self.counters["unknown_member_rejects"] += 1
                raise UnknownMember(
                    f"renew from unregistered {replica_id} (directory "
                    f"restart or confirmed death); re-register")
            if int(fence) != m.fence:
                self.counters["stale_fence_rejects"] += 1
                raise StaleFencingToken(
                    f"renew of {replica_id} with fence {fence} "
                    f"rejected: current fence is {m.fence}")
            now = self._now()
            if now > m.lease_expires:
                self.counters["late_renewals"] += 1
            m.lease_expires = now + self.lease_ttl_s
            if digest is not None:
                m.digest = list(digest)
            if load is not None:
                m.load = dict(load)
            if wedged and not m.wedged:
                self.counters["wedges_reported"] += 1
            m.wedged = bool(wedged)
            self.counters["renews"] += 1
            return {"lease_ttl_s": self.lease_ttl_s}

    def rpc_deregister(self, replica_id: str,
                       fence: int) -> Dict[str, Any]:
        with self._lock:
            m = self._members.get(replica_id)
            if m is None:
                raise UnknownMember(f"{replica_id} not registered")
            if int(fence) != m.fence:
                self.counters["stale_fence_rejects"] += 1
                raise StaleFencingToken(
                    f"deregister of {replica_id} with fence {fence} "
                    f"rejected: current fence is {m.fence}")
            del self._members[replica_id]
            self._tombstones[replica_id] = max(
                self._tombstones.get(replica_id, -1), m.generation)
            self.counters["deregisters"] += 1
            return {"ok": True}

    def rpc_confirm_dead(self, replica_id: str,
                         fence: int) -> Dict[str, Any]:
        """Adjudicate a router's suspicion. Dead means: unknown id,
        a superseded fence (the incarnation the router talked to is
        gone), or an expired lease (which this call then reaps). A
        member with a live lease is NOT dead, however the transport
        to it looked from the router's side."""
        with self._lock:
            m = self._members.get(replica_id)
            if m is None:
                return {"dead": True, "reason": "unknown"}
            if int(fence) != m.fence:
                return {"dead": True, "reason": "superseded",
                        "current_fence": m.fence}
            now = self._now()
            if now <= m.lease_expires:
                return {"dead": False,
                        "lease_remaining_s":
                            m.lease_expires - now}
            del self._members[replica_id]
            self._tombstones[replica_id] = max(
                self._tombstones.get(replica_id, -1), m.generation)
            self.counters["confirmed_dead"] += 1
            return {"dead": True, "reason": "lease_expired",
                    "expired_for_s": now - m.lease_expires}

    def rpc_snapshot(self) -> Dict[str, Any]:
        with self._lock:
            now = self._now()
            members = [{
                "replica_id": m.replica_id, "addr": m.addr,
                "generation": m.generation, "fence": m.fence,
                "lease_remaining_s": m.lease_expires - now,
                "expired": now > m.lease_expires,
                "wedged": m.wedged, "digest": m.digest,
                "load": m.load, "page_size": m.page_size,
            } for m in self._members.values()]
            return {"members": members,
                    "fence_counter": self._fence_counter,
                    "lease_ttl_s": self.lease_ttl_s}

    def rpc_stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"members": len(self._members),
                    "fence_counter": self._fence_counter,
                    "tombstones": dict(self._tombstones),
                    "counters": dict(self.counters)}


class DirectoryClient:
    """Typed client wrapper over any transport to a directory."""

    def __init__(self, transport: Transport,
                 timeout_s: float = 2.0):
        self._t = transport
        self._timeout_s = timeout_s

    def ping(self) -> Dict[str, Any]:
        return self._t.call("ping", {}, timeout_s=self._timeout_s)

    def register(self, replica_id: str, addr: List[Any],
                 generation: int, page_size: int = 0,
                 min_fence: int = 0) -> Dict[str, Any]:
        return self._t.call(
            "register",
            {"replica_id": replica_id, "addr": addr,
             "generation": generation, "page_size": page_size,
             "min_fence": min_fence},
            timeout_s=self._timeout_s)

    def renew(self, replica_id: str, fence: int,
              digest: Optional[List[int]] = None,
              load: Optional[Dict[str, Any]] = None,
              wedged: bool = False) -> Dict[str, Any]:
        return self._t.call(
            "renew",
            {"replica_id": replica_id, "fence": fence,
             "digest": digest, "load": load, "wedged": wedged},
            timeout_s=self._timeout_s)

    def deregister(self, replica_id: str,
                   fence: int) -> Dict[str, Any]:
        return self._t.call(
            "deregister",
            {"replica_id": replica_id, "fence": fence},
            timeout_s=self._timeout_s)

    def confirm_dead(self, replica_id: str,
                     fence: int) -> Dict[str, Any]:
        return self._t.call(
            "confirm_dead",
            {"replica_id": replica_id, "fence": fence},
            timeout_s=self._timeout_s)

    def snapshot(self) -> Dict[str, Any]:
        return self._t.call("snapshot", {},
                            timeout_s=self._timeout_s)

    def stats(self) -> Dict[str, Any]:
        return self._t.call("stats", {}, timeout_s=self._timeout_s)


def main(argv: Optional[List[str]] = None) -> None:
    """Subprocess entry: ``python -m ray_tpu.serve.fleet.directory
    --port N``. Prints ``READY <port>`` once listening."""
    import argparse
    import sys

    from ray_tpu.serve.fleet.transport import SocketServer

    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--lease-ttl-s", type=float, default=1.0)
    args = ap.parse_args(argv)

    directory = FleetDirectory(lease_ttl_s=args.lease_ttl_s)
    server = SocketServer(directory.handle, host=args.host,
                          port=args.port)
    print(f"READY {server.addr[1]}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()


if __name__ == "__main__":
    main()
