"""Fleet-wide observability plane: scrape, align, stitch, bundle.

PR 12/13 split serving into real OS processes; this module is how an
operator sees them as ONE system again. Every fleet role serves
``rpc_telemetry`` over the existing transport seam — a snapshot of its
Prometheus exposition, a cursored window of its local event log, and a
monotonic+wall clock sample. The router-side ``TelemetryCollector``
periodically scrapes all live members and solves the three problems a
multi-process timeline has:

- **Duplication.** Scrapes resume from a per-member cursor, so a
  collector restart or a slow poll never re-ingests events; a member
  restart (new pid/generation) resets the cursor — its monotonic base
  is new, so its old cursor is meaningless anyway. Events the ring
  overwrote before the scrape caught up are COUNTED (``dropped``),
  never silently skipped.

- **Unsynchronized clocks.** Each member stamps events with its own
  ``time.monotonic()``; bases differ per process and reset on
  restart. The collector estimates the per-member offset NTP-style
  from RPC send/receive timestamps: for a call sampled ``t0`` (local
  send) / ``t3`` (local receive) carrying the member's clock ``t1``,
  ``offset = t1 - (t0 + t3)/2`` with uncertainty ``(t3 - t0)/2`` —
  the true offset is provably within +-RTT/2 of the estimate,
  whatever the request/response asymmetry. The minimum-RTT sample
  wins (tightest bound); drift is measured across samples. Every
  merged event carries ``local_t = t - offset``: the collector's own
  timebase.

- **Disjoint request timelines.** ``request_phases()`` groups the
  merged stream by ``trace_id`` and emits per-process spans stamped
  with role/replica_id/pid/generation, so one request's
  proxy -> router -> agent (-> resubmit agent) hops read as one
  aligned timeline; ``chrome_trace()`` exports the same thing for
  ui.perfetto.dev with one process row per member incarnation.

The **cluster flight recorder** extends PR 10's per-process bundles:
on a confirmed death, self-fence, wedge, or primary failover the
collector pulls fresh telemetry from every reachable role and writes
one ``cluster-...`` bundle directory — a manifest with the trigger,
member coverage, and the clock-offset table, plus per-member event
files and the merged offset-corrected stream — so a single artifact
explains the fault end-to-end (asserted by ``tools/chaos_serve.py
--fleet``).
"""
from __future__ import annotations

import itertools
import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.serve import obs

# event kinds that mean "a fault the operator will ask about": the
# collector reacts to these in freshly scraped streams by pulling a
# cluster bundle (confirmed deaths arrive via the router hook instead,
# so they fire even when the dead member can no longer be scraped)
FAULT_ETYPES = ("self_fence", "wedged", "promote", "recover")

_bundle_seq = itertools.count()


def _slug(s: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]+", "-", str(s))[:80] or "bundle"


class ClockOffsetEstimator:
    """NTP-style offset between the collector's monotonic clock and
    ONE member incarnation's monotonic clock.

    ``add_sample(t0, t1, t3)`` ingests one RPC round trip; the best
    (minimum-RTT) sample provides ``offset_s`` with
    ``uncertainty_s = RTT/2`` — an asymmetric network can push the
    true offset anywhere inside that bound, never outside it.
    ``drift_s_per_s`` is the observed offset slope between the first
    and latest samples' local midpoints: nonzero means the two clocks
    tick at measurably different rates (or the member restarted —
    which the collector rules out by keying estimators per
    incarnation)."""

    def __init__(self, max_samples: int = 64,
                 min_drift_window_s: float = 1.0):
        self.max_samples = int(max_samples)
        # drift over a tiny baseline is all RTT-asymmetry noise: the
        # slope only means something once the samples span a window
        # much longer than one round trip
        self.min_drift_window_s = float(min_drift_window_s)
        self._samples: List[tuple] = []   # (local_mid, offset, unc)
        self.offset_s: Optional[float] = None
        self.uncertainty_s: Optional[float] = None
        self.rtt_s: Optional[float] = None
        self.n_samples = 0

    def add_sample(self, t0: float, t1: float, t3: float) -> None:
        if t3 < t0:
            raise ValueError(f"receive time {t3} precedes send "
                             f"time {t0}")
        rtt = t3 - t0
        mid = 0.5 * (t0 + t3)
        offset = t1 - mid
        unc = 0.5 * rtt
        self.n_samples += 1
        self._samples.append((mid, offset, unc))
        if len(self._samples) > self.max_samples:
            self._samples.pop(0)
        if self.uncertainty_s is None or unc <= self.uncertainty_s:
            self.offset_s = offset
            self.uncertainty_s = unc
            self.rtt_s = rtt

    @property
    def drift_s_per_s(self) -> Optional[float]:
        if len(self._samples) < 2:
            return None
        m0, o0, _ = self._samples[0]
        m1, o1, _ = self._samples[-1]
        if m1 - m0 < self.min_drift_window_s:
            return None
        return (o1 - o0) / (m1 - m0)

    def to_local(self, remote_t: float) -> Optional[float]:
        """Map a member-clock timestamp onto the collector's
        monotonic timebase."""
        if self.offset_s is None:
            return None
        return remote_t - self.offset_s

    def as_dict(self) -> Dict[str, Any]:
        rnd = (lambda v: None if v is None else round(v, 9))
        return {"offset_s": rnd(self.offset_s),
                "uncertainty_s": rnd(self.uncertainty_s),
                "rtt_s": rnd(self.rtt_s),
                "drift_s_per_s": rnd(self.drift_s_per_s),
                "n_samples": self.n_samples}


class _MemberState:
    """Collector-side state for one member NAME (replica_id /
    "directory" / "router"); the incarnation key (replica_id, pid,
    generation) resets the cursor and estimator on restart."""

    __slots__ = ("name", "role", "key", "estimator", "cursor",
                 "cursors", "estimators",
                 "metrics_text", "last_scrape_mono", "last_payload",
                 "dropped", "events_total", "up", "last_error",
                 "incarnations", "scrapes")

    def __init__(self, name: str, role: str):
        self.name = name
        self.role = role
        self.key: Optional[tuple] = None
        self.estimator = ClockOffsetEstimator()
        self.cursor = 0
        # per-incarnation read state: one NAME (e.g. "directory")
        # can alternate between processes behind a failover client,
        # and each process restarts its event seqs and its monotonic
        # clock at zero — a shared cursor would either skip a fresh
        # incarnation's whole log or re-ingest an old one's
        self.cursors: Dict[tuple, int] = {}
        self.estimators: Dict[tuple, ClockOffsetEstimator] = {}
        self.metrics_text = ""
        self.last_scrape_mono: Optional[float] = None
        self.last_payload: Optional[Dict[str, Any]] = None
        self.dropped = 0
        self.events_total = 0
        self.up = False
        self.last_error: Optional[str] = None
        self.incarnations = 0
        self.scrapes = 0

    def summary(self, now: float) -> Dict[str, Any]:
        out = {
            "name": self.name,
            "role": self.role,
            "up": self.up,
            "pid": self.key[1] if self.key else None,
            "generation": self.key[2] if self.key else None,
            "incarnations": self.incarnations,
            "scrapes": self.scrapes,
            "scrape_age_s": (
                round(now - self.last_scrape_mono, 6)
                if self.last_scrape_mono is not None else None),
            "dropped": self.dropped,
            "events_total": self.events_total,
            "last_error": self.last_error,
        }
        out.update(self.estimator.as_dict())
        return out


def _fleet_metrics():
    """serve_fleet_* collector gauges (same lazy rebuild-after-
    clear_registry pattern as ``obs.phase_metrics``)."""
    from ray_tpu.util import metrics
    global _METRICS
    reg = metrics.registry()
    if _METRICS is not None and all(
            m.name in reg for m in _METRICS.values()):
        return _METRICS
    _METRICS = {
        "up": metrics.Gauge(
            "serve_fleet_member_up",
            "1 while the member answered its latest scrape",
            tag_keys=("member",)),
        "offset": metrics.Gauge(
            "serve_fleet_clock_offset_s",
            "estimated member-clock minus collector-clock offset",
            tag_keys=("member",)),
        "uncertainty": metrics.Gauge(
            "serve_fleet_clock_uncertainty_s",
            "RTT/2 bound on the offset estimate",
            tag_keys=("member",)),
        "scrape_age": metrics.Gauge(
            "serve_fleet_scrape_age_s",
            "seconds since the member's last successful scrape",
            tag_keys=("member",)),
        "dropped": metrics.Gauge(
            "serve_fleet_dropped_events",
            "events the member ring overwrote before the scrape "
            "caught up",
            tag_keys=("member",)),
        "scrape_errors": metrics.Counter(
            "serve_fleet_scrape_errors_total",
            "failed member scrapes", tag_keys=("member",)),
        "members": metrics.Gauge(
            "serve_fleet_members", "members under scrape"),
        "bundles": metrics.Counter(
            "serve_fleet_cluster_bundles_total",
            "cluster flight bundles written"),
    }
    return _METRICS


_METRICS: Optional[Dict[str, Any]] = None


class TelemetryCollector:
    """Router-side scrape loop + merged cluster event stream.

    The collector rides the router's own seams: ``router._snapshot()``
    for membership, ``router._agent(member)`` for cached typed
    clients, and ``router._directory`` for the control plane — it
    adds no second discovery path that could disagree with routing.
    The router's OWN event log is ingested as a member too (offset 0:
    same process), so the merged stream covers every role.
    """

    def __init__(self, router, *, interval_s: float = 0.25,
                 events_per_scrape: int = 512,
                 cluster_dir: Optional[str] = None,
                 offset_bound_s: Optional[float] = None,
                 max_merged_events: int = 65536):
        self._router = router
        self.interval_s = float(interval_s)
        self.events_per_scrape = int(events_per_scrape)
        self.cluster_dir = cluster_dir
        self.offset_bound_s = offset_bound_s
        self.max_merged_events = int(max_merged_events)
        self._lock = threading.Lock()
        # serializes whole scrape passes (periodic loop vs. the
        # router's confirmed-death hook): two concurrent passes
        # would fetch the same window with the same cursor and
        # ingest it twice. RLock: a fault found mid-scrape pulls a
        # bundle, whose own scrape re-enters on the same thread.
        self._scrape_lock = threading.RLock()
        self._members: Dict[str, _MemberState] = {}
        self._merged: List[Dict[str, Any]] = []
        self._merged_dropped = 0
        self._seen_faults: set = set()
        self.bundles: List[Dict[str, Any]] = []
        self.counters = {"scrapes": 0, "scrape_errors": 0,
                         "events_ingested": 0, "bundles": 0}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------ lifecycle

    def attach(self) -> "TelemetryCollector":
        """Hook the router's confirmed-death path: a death pulls a
        cluster bundle, not just the router's local one."""
        self._router.telemetry_collector = self
        return self

    def run(self, interval_s: Optional[float] = None
            ) -> "TelemetryCollector":
        if interval_s is not None:
            self.interval_s = float(interval_s)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="telemetry-collector",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:
                pass
            self._stop.wait(self.interval_s)

    # --------------------------------------------------------- scrape

    def _state(self, name: str, role: str) -> _MemberState:
        st = self._members.get(name)
        if st is None:
            st = self._members[name] = _MemberState(name, role)
        return st

    def _ingest(self, st: _MemberState,
                payload: Dict[str, Any],
                t0: float, t3: float) -> List[Dict[str, Any]]:
        """Fold one telemetry response into the member state and the
        merged stream; returns the NEW normalized events."""
        key = (payload.get("replica_id"), payload.get("pid"),
               payload.get("generation"))
        clock = payload.get("clock") or {}
        with self._lock:
            if key != st.key:
                # new (or resumed) incarnation: its monotonic base
                # and its event seqs restarted with the process, so
                # cursor and offset estimate are kept PER key — the
                # previous incarnation's describe another process
                st.key = key
                st.incarnations += 1
                st.cursor = st.cursors.get(key, 0)
                est = st.estimators.get(key)
                if est is None:
                    est = st.estimators[key] = ClockOffsetEstimator()
                    while len(st.estimators) > 32:
                        dead = next(iter(st.estimators))
                        st.estimators.pop(dead, None)
                        st.cursors.pop(dead, None)
                st.estimator = est
            st.estimator.add_sample(t0, float(clock["mono"]), t3)
            est = st.estimator
            fresh = [e for e in payload.get("events", [])
                     if e.get("seq", 0) >= st.cursor]
            st.cursor = int(payload.get("cursor", st.cursor))
            st.cursors[key] = st.cursor
            st.dropped += int(payload.get("dropped", 0))
            st.events_total = int(payload.get("events_total", 0))
            st.metrics_text = payload.get("metrics_text", "")
            st.last_payload = payload
            st.last_scrape_mono = time.monotonic()
            st.up = True
            st.last_error = None
            st.scrapes += 1
            out = []
            for e in fresh:
                ev = {
                    "member": st.name,
                    "role": payload.get("role"),
                    "pid": payload.get("pid"),
                    "generation": payload.get("generation"),
                    "seq": e.get("seq"),
                    "t": e.get("t"),
                    "local_t": (round(est.to_local(e["t"]), 9)
                                if isinstance(e.get("t"),
                                              (int, float))
                                else None),
                    "offset_uncertainty_s": round(
                        est.uncertainty_s, 9),
                    "type": e.get("type"),
                    "rid": e.get("rid"),
                    "data": e.get("data"),
                }
                out.append(ev)
            self._merged.extend(out)
            self.counters["events_ingested"] += len(out)
            if len(self._merged) > self.max_merged_events:
                cut = len(self._merged) - self.max_merged_events
                del self._merged[:cut]
                self._merged_dropped += cut
        return out

    def _router_payload(self) -> Dict[str, Any]:
        """The router's local log in the same shape the RPC returns
        (offset trivially 0: same process, same clock)."""
        from ray_tpu.util import metrics
        r = self._router
        window, next_cursor, dropped = obs.event_window(
            r.events.snapshot(), r.events.total,
            self._state("router", "router").cursor,
            self.events_per_scrape)
        return {
            "role": "router", "replica_id": "router",
            "generation": 0, "fence": None, "pid": os.getpid(),
            "clock": {"mono": time.monotonic(),
                      "wall": time.time()},
            "metrics_text": metrics.prometheus_text(),
            "events": obs.as_dicts(window),
            "cursor": next_cursor,
            "events_total": r.events.total,
            "dropped": dropped,
        }

    def _scrape_remote(self, st: _MemberState,
                       fetch) -> List[Dict[str, Any]]:
        """Fetch one member's telemetry with the cursor that belongs
        to whichever incarnation actually answers.

        The first fetch necessarily uses the LAST incarnation's
        cursor; if the payload names a different (replica_id, pid,
        generation) — a restart, or a failover client switching
        endpoints — that window was filtered with a cursor from
        another process's seq space and may have dropped the new
        incarnation's entire log (its seqs restarted at 0). Refetch
        with the answering incarnation's own cursor before ingesting.
        """
        with self._lock:
            cursor = st.cursor
        t0 = time.monotonic()
        payload = fetch(cursor)
        t3 = time.monotonic()
        key = (payload.get("replica_id"), payload.get("pid"),
               payload.get("generation"))
        with self._lock:
            own = cursor if key == st.key \
                else st.cursors.get(key, 0)
        if own != cursor:
            t0 = time.monotonic()
            payload = fetch(own)
            t3 = time.monotonic()
        return self._ingest(st, payload, t0, t3)

    def scrape_once(self) -> Dict[str, Any]:
        """One pass over router + directory + every live agent.
        Returns {member_name: n_new_events_or_None}."""
        with self._scrape_lock:
            return self._scrape_all()

    def _scrape_all(self) -> Dict[str, Any]:
        results: Dict[str, Any] = {}
        fresh: List[Dict[str, Any]] = []

        st = self._state("router", "router")
        payload = self._router_payload()
        # same process, same clock: the "round trip" is a function
        # call, so the sample is exact (offset 0, uncertainty 0)
        t_self = payload["clock"]["mono"]
        fresh += self._ingest(st, payload, t_self, t_self)
        results["router"] = len(fresh)

        # a FailoverDirectoryClient fronts several directory
        # PROCESSES (primary + standbys); scrape each endpoint
        # directly, or the active-endpoint indirection would hide a
        # restarted primary's early events (its "recover") whenever
        # the client happens to be parked on the standby
        dirc = self._router._directory
        endpoints = getattr(dirc, "_clients", None) or [dirc]
        for i, cl in enumerate(endpoints):
            nm = "directory" if len(endpoints) == 1 \
                else f"directory-{i}"
            st = self._state(nm, "directory")
            try:
                new = self._scrape_remote(
                    st, lambda c, _cl=cl: _cl.telemetry(
                        cursor=c, limit=self.events_per_scrape))
                fresh += new
                results[nm] = len(new)
            except Exception as e:   # noqa: BLE001
                self._mark_down(st, e)
                results[nm] = None

        try:
            members = self._router._snapshot()
        except Exception:
            members = {}
        for rid, member in sorted(members.items()):
            st = self._state(rid, "agent")
            try:
                client = self._router._agent(member)
                new = self._scrape_remote(
                    st, lambda c, _cl=client: _cl.telemetry(
                        cursor=c, limit=self.events_per_scrape))
                fresh += new
                results[rid] = len(new)
            except Exception as e:   # noqa: BLE001
                self._mark_down(st, e)
                results[rid] = None

        with self._lock:
            self.counters["scrapes"] += 1
        self._export_gauges()
        self._scan_for_faults(fresh)
        return results

    def _mark_down(self, st: _MemberState, err: BaseException) -> None:
        with self._lock:
            st.up = False
            st.last_error = type(err).__name__
            self.counters["scrape_errors"] += 1
        try:
            _fleet_metrics()["scrape_errors"].inc(
                tags={"member": st.name})
        except Exception:
            pass

    def _export_gauges(self) -> None:
        try:
            m = _fleet_metrics()
            now = time.monotonic()
            with self._lock:
                states = list(self._members.values())
            m["members"].set(len(states))
            for st in states:
                tags = {"member": st.name}
                m["up"].set(1.0 if st.up else 0.0, tags=tags)
                m["dropped"].set(st.dropped, tags=tags)
                if st.last_scrape_mono is not None:
                    m["scrape_age"].set(
                        now - st.last_scrape_mono, tags=tags)
                if st.estimator.offset_s is not None:
                    m["offset"].set(st.estimator.offset_s,
                                    tags=tags)
                    m["uncertainty"].set(
                        st.estimator.uncertainty_s, tags=tags)
        except Exception:
            pass

    # ---------------------------------------------- fault -> bundle

    def _scan_for_faults(self, fresh: List[Dict[str, Any]]) -> None:
        for ev in fresh:
            if ev.get("type") not in FAULT_ETYPES:
                continue
            tag = (ev.get("member"), ev.get("pid"), ev.get("seq"),
                   ev.get("type"))
            with self._lock:
                if tag in self._seen_faults:
                    continue
                self._seen_faults.add(tag)
            self.on_fault(
                f"{ev['type']}-{ev.get('member')}",
                trigger={"kind": ev["type"],
                         "member": ev.get("member"),
                         "role": ev.get("role"),
                         "pid": ev.get("pid"),
                         "generation": ev.get("generation"),
                         "seq": ev.get("seq"),
                         "data": ev.get("data")})

    def on_fault(self, reason: str,
                 trigger: Optional[Dict[str, Any]] = None
                 ) -> Optional[str]:
        """Confirmed death / fence / wedge / failover: pull fresh
        telemetry from every reachable role and write ONE bundle
        that explains the fault cluster-wide."""
        if self.cluster_dir is None:
            return None
        try:
            self.scrape_once()
        except Exception:
            pass
        return self.dump_cluster_bundle(reason, trigger=trigger)

    def dump_cluster_bundle(self, reason: str,
                            trigger: Optional[Dict[str, Any]] = None
                            ) -> Optional[str]:
        """Write ``<cluster_dir>/cluster-<reason>-<seq>/``:
        ``manifest.json`` (trigger, member coverage, offset table,
        collector health), one ``member-*.json`` per member with its
        retained telemetry, and ``events.jsonl`` — the merged
        offset-corrected stream, one event per line, sorted on the
        collector's timebase. Never raises: a recorder that faults
        during a fault is worse than none."""
        root = self.cluster_dir
        if root is None:
            return None
        bdir = os.path.join(root, "cluster-%s-%06d" % (
            _slug(reason), next(_bundle_seq)))
        try:
            with self._lock:
                states = {n: st for n, st in self._members.items()}
                merged = list(self._merged)
            now = time.monotonic()
            manifest = {
                "reason": str(reason),
                "trigger": trigger,
                "t_wall": time.time(),
                "t_mono": now,
                "collector_pid": os.getpid(),
                "members": {n: st.summary(now)
                            for n, st in states.items()},
                "offset_table": {n: st.estimator.as_dict()
                                 for n, st in states.items()},
                "coverage": {
                    "scraped": sorted(n for n, st in states.items()
                                      if st.up),
                    "unreachable": sorted(
                        n for n, st in states.items() if not st.up),
                },
                "health": self.health(),
                "merged_events": len(merged),
            }
            os.makedirs(bdir, exist_ok=True)
            with open(os.path.join(bdir, "manifest.json"),
                      "w") as f:
                json.dump(manifest, f, indent=2, default=repr)
            for n, st in states.items():
                if st.last_payload is None:
                    continue
                fname = "member-%s-p%s-g%s.json" % (
                    _slug(n), (st.key or (None, "x", None))[1],
                    (st.key or (None, None, "x"))[2])
                with open(os.path.join(bdir, fname), "w") as f:
                    json.dump(st.last_payload, f, indent=2,
                              default=repr)
            with open(os.path.join(bdir, "events.jsonl"),
                      "w") as f:
                for ev in sorted(
                        merged,
                        key=lambda e: (e.get("local_t")
                                       if e.get("local_t")
                                       is not None else 0.0)):
                    f.write(json.dumps(ev, default=repr) + "\n")
        except OSError:
            return None
        row = {"path": bdir, "reason": str(reason),
               "trigger": trigger}
        with self._lock:
            self.bundles.append(row)
            self.counters["bundles"] += 1
        try:
            _fleet_metrics()["bundles"].inc()
        except Exception:
            pass
        return bdir

    # ------------------------------------------------------ read side

    def members(self) -> Dict[str, Dict[str, Any]]:
        now = time.monotonic()
        with self._lock:
            return {n: st.summary(now)
                    for n, st in self._members.items()}

    def merged_events(self) -> List[Dict[str, Any]]:
        """The offset-corrected cluster stream, sorted on the
        collector's timebase."""
        with self._lock:
            merged = list(self._merged)
        return sorted(merged,
                      key=lambda e: (e.get("local_t")
                                     if e.get("local_t") is not None
                                     else 0.0))

    def health(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            states = list(self._members.values())
            counters = dict(self.counters)
            merged_n = len(self._merged)
            merged_dropped = self._merged_dropped
        ages = [now - st.last_scrape_mono for st in states
                if st.last_scrape_mono is not None]
        uncs = [st.estimator.uncertainty_s for st in states
                if st.estimator.uncertainty_s is not None]
        drifts = [abs(st.estimator.drift_s_per_s) for st in states
                  if st.estimator.drift_s_per_s is not None]
        return {
            "members": len(states),
            "members_up": sum(1 for st in states if st.up),
            "counters": counters,
            "max_scrape_age_s": (round(max(ages), 6)
                                 if ages else None),
            "max_offset_uncertainty_s": (round(max(uncs), 9)
                                         if uncs else None),
            "max_abs_drift_s_per_s": (round(max(drifts), 9)
                                      if drifts else None),
            "dropped_events": sum(st.dropped for st in states),
            "merged_events": merged_n,
            "merged_dropped": merged_dropped,
            "offset_bound_s": self.offset_bound_s,
            "offset_within_bound": (
                None if self.offset_bound_s is None or not uncs
                else bool(max(uncs) <= self.offset_bound_s)),
        }

    def request_phases(self) -> Dict[str, Dict[str, Any]]:
        """Cross-process request stitching, keyed by trace_id.

        For every trace_id in the merged stream: the per-member spans
        (first to last event that member logged for the trace, each
        stamped role/replica_id/pid/generation and placed on the
        aligned timebase), the set of OS processes touched, and
        whether the trace STITCHED (>= 2 distinct pids — the whole
        point of the aligned timebase)."""
        by_trace: Dict[str, List[Dict[str, Any]]] = {}
        for ev in self.merged_events():
            data = ev.get("data")
            tid = data.get("trace_id") if isinstance(data, dict) \
                else None
            if tid:
                by_trace.setdefault(str(tid), []).append(ev)
        out: Dict[str, Dict[str, Any]] = {}
        for tid, evs in by_trace.items():
            spans = []
            by_member: Dict[tuple, List[Dict[str, Any]]] = {}
            for ev in evs:
                by_member.setdefault(
                    (ev["member"], ev["pid"], ev["generation"]),
                    []).append(ev)
            for (member, pid, gen), mevs in sorted(
                    by_member.items(),
                    key=lambda kv: kv[1][0]["local_t"] or 0.0):
                ts = [e["local_t"] for e in mevs
                      if e["local_t"] is not None]
                if not ts:
                    continue
                spans.append({
                    "role": mevs[0]["role"],
                    "replica_id": member,
                    "pid": pid,
                    "generation": gen,
                    "start_s": round(min(ts), 9),
                    "end_s": round(max(ts), 9),
                    "offset_uncertainty_s": max(
                        e.get("offset_uncertainty_s") or 0.0
                        for e in mevs),
                    "etypes": [e["type"] for e in mevs],
                    "rids": sorted({str(e["rid"]) for e in mevs
                                    if e.get("rid") is not None}),
                })
            pids = sorted({s["pid"] for s in spans
                           if s["pid"] is not None})
            out[tid] = {
                "trace_id": tid,
                "spans": spans,
                "processes": pids,
                "n_processes": len(pids),
                "members": sorted({s["replica_id"]
                                   for s in spans}),
                "stitched": len(pids) >= 2,
                "events": len(evs),
            }
        return out

    def chrome_trace(self) -> List[Dict[str, Any]]:
        """Merged stream as Chrome trace events: one process row per
        member incarnation (real pids), request spans as complete
        ('X') events under their trace_id track, every raw event as
        an instant."""
        out: List[Dict[str, Any]] = []
        seen_procs = set()
        for ev in self.merged_events():
            pid = ev.get("pid")
            if pid is None or ev.get("local_t") is None:
                continue
            if pid not in seen_procs:
                seen_procs.add(pid)
                out.append({
                    "ph": "M", "name": "process_name", "pid": pid,
                    "tid": 0,
                    "args": {"name": "%s:%s:g%s" % (
                        ev.get("role"), ev.get("member"),
                        ev.get("generation"))}})
            out.append({
                "ph": "i", "s": "t", "pid": pid,
                "tid": str(ev.get("rid") or ev.get("member")),
                "name": ev.get("type"),
                "ts": round(ev["local_t"] * 1e6, 3),
                "args": {"seq": ev.get("seq"),
                         "member": ev.get("member"),
                         "data": ev.get("data")}})
        for tid, ph in sorted(self.request_phases().items()):
            for span in ph["spans"]:
                out.append({
                    "ph": "X", "pid": span["pid"],
                    "tid": f"trace:{tid}",
                    "name": "%s %s" % (span["role"],
                                       span["replica_id"]),
                    "ts": round(span["start_s"] * 1e6, 3),
                    "dur": round(max(span["end_s"]
                                     - span["start_s"],
                                     1e-6) * 1e6, 3),
                    "args": {"trace_id": tid,
                             "generation": span["generation"],
                             "offset_uncertainty_s":
                                 span["offset_uncertainty_s"],
                             "etypes": span["etypes"]}})
        return out

    def metrics_text(self) -> str:
        """The aggregated exposition the proxy serves: every member's
        scraped families re-labeled ``member=<name>`` plus the
        collector's own (local-registry) health gauges."""
        self._export_gauges()
        with self._lock:
            texts = {st.name: st.metrics_text
                     for st in self._members.values()
                     if st.metrics_text}
        from ray_tpu.util import metrics
        return merge_prometheus_texts(texts) + metrics.prometheus_text()


def merge_prometheus_texts(texts: Dict[str, str],
                           label: str = "member") -> str:
    """Merge per-member Prometheus expositions into one, injecting
    ``label="<member>"`` into every sample so same-named families
    from N processes stay distinguishable. HELP/TYPE are emitted once
    per family; members and families are sorted, so (given the
    deterministic per-process exposition) the merge is diffable."""
    from ray_tpu.util.metrics import _escape_label
    families: Dict[str, Dict[str, Any]] = {}
    for member in sorted(texts):
        fam = None
        for line in texts[member].splitlines():
            if not line:
                continue
            if line.startswith("# HELP "):
                fam = line.split(" ", 3)[2]
                families.setdefault(fam, {"help": line,
                                          "type": None,
                                          "samples": []})
                continue
            if line.startswith("# TYPE "):
                if fam is not None:
                    families[fam]["type"] = \
                        families[fam]["type"] or line
                continue
            if fam is None:
                continue
            try:
                head, value = line.rsplit(" ", 1)
            except ValueError:
                continue
            inject = f'{label}="{_escape_label(member)}"'
            if head.endswith("}"):
                i = head.index("{")
                head = f"{head[:i]}{{{inject},{head[i + 1:]}"
            else:
                head = f"{head}{{{inject}}}"
            families[fam]["samples"].append(f"{head} {value}")
    lines: List[str] = []
    for fam in sorted(families):
        f = families[fam]
        lines.append(f["help"])
        if f["type"]:
            lines.append(f["type"])
        lines.extend(f["samples"])
    return ("\n".join(lines) + "\n") if lines else ""


def load_cluster_bundle(bdir: str) -> Dict[str, Any]:
    """Read a cluster bundle back: the manifest plus its merged
    event stream (``events.jsonl`` parsed with the same torn-tail
    tolerance as ``obs.load_flight_bundle``) and the per-member
    payload files."""
    with open(os.path.join(bdir, "manifest.json")) as f:
        manifest = json.load(f)
    events: List[Dict[str, Any]] = []
    epath = os.path.join(bdir, "events.jsonl")
    torn = 0
    if os.path.exists(epath):
        with open(epath) as f:
            raw = f.read()
        lines = raw.split("\n")
        complete, fragment = lines[:-1], lines[-1]
        for i, line in enumerate(complete):
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                if i != len(complete) - 1 or fragment:
                    raise
                torn += 1
                break
        if fragment:
            torn += 1
    members: Dict[str, Any] = {}
    for fname in sorted(os.listdir(bdir)):
        if fname.startswith("member-") and fname.endswith(".json"):
            with open(os.path.join(bdir, fname)) as f:
                members[fname[len("member-"):-len(".json")]] = \
                    json.load(f)
    manifest["events"] = events
    manifest["events_torn_truncated"] = torn
    manifest["member_payloads"] = members
    return manifest
