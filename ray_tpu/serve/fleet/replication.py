"""Hot-standby replication for the FleetDirectory.

Three pieces, all speaking the existing ``Transport`` seam:

- ``Replicator`` (primary side): an async publisher streaming every
  membership delta (``repl_apply``) to >= 1 standby, with full-state
  ``repl_sync`` bootstrap/repair whenever a standby was unreachable
  or behind. Publishing never blocks the mutating RPC — the primary
  acknowledges from its own WAL; replication is the availability
  layer, not the durability layer.
- ``StandbyMonitor`` (standby side): pings the primary and promotes
  the LOCAL standby after ``promote_after_s`` of continuous silence
  — but only once it has seen the primary alive at least once, so a
  standby booted before its primary doesn't steal the throne at
  startup. Promotion itself (``FleetDirectory.rpc_promote``) folds
  an epoch bump into the fence counter so no fencing token regresses
  across failover even if the last deltas never arrived.
- ``FailoverDirectoryClient``: the ordered-endpoint-list client that
  routers and agents hold. Every call starts at the last endpoint
  that answered; ``TransportError`` and typed ``NotPrimary`` advance
  to the next endpoint, every OTHER typed error propagates untouched
  (a ``StaleFencingToken`` from the real primary is an answer, not
  an outage). Layered UNDER the router's stale-snapshot fallback:
  the router only sees a failure when every endpoint refused.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.serve.fleet.directory import (FENCE_EPOCH_STRIDE,
                                           PRIMARY, DirectoryClient)
from ray_tpu.serve.fleet.transport import Transport, TransportError
from ray_tpu.serve.fleet.wire import NotPrimary

__all__ = ["Replicator", "StandbyMonitor",
           "FailoverDirectoryClient", "FENCE_EPOCH_STRIDE"]


class Replicator:
    """Primary-side delta stream to an ordered set of standbys."""

    def __init__(self, transports: List[Transport], *,
                 timeout_s: float = 1.5, maxlen: int = 8192):
        self._standbys = [{"t": t, "needs_sync": True,
                           "superseded": False}
                          for t in transports]
        self._timeout_s = timeout_s
        self._dir = None
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: collections.deque = collections.deque(
            maxlen=maxlen)
        self._seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stats = {"published": 0, "applied": 0, "syncs": 0,
                      "errors": 0, "superseded": 0}

    def attach(self, directory) -> "Replicator":
        self._dir = directory
        return self

    def start(self) -> "Replicator":
        self._thread = threading.Thread(target=self._run,
                                        name="fleet-replicator",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def publish(self, epoch: int, record: Dict[str, Any]) -> None:
        """Enqueue one delta (non-blocking; called under the
        directory's lock)."""
        with self._cv:
            self._seq += 1
            self._queue.append((self._seq, int(epoch), dict(record)))
            self.stats["published"] += 1
            self._cv.notify()

    def _state(self):
        d = self._dir
        with d._lock:
            return d.epoch, d._durable_payload()

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._cv:
                while not self._queue and not self._stop.is_set():
                    self._cv.wait(timeout=0.25)
                if self._stop.is_set():
                    return
                seq, epoch, record = self._queue.popleft()
            for sb in self._standbys:
                if sb["superseded"]:
                    continue
                try:
                    if sb["needs_sync"]:
                        cur_epoch, state = self._state()
                        sb["t"].call(
                            "repl_sync",
                            {"epoch": cur_epoch, "seq": seq - 1,
                             "state": state},
                            timeout_s=self._timeout_s)
                        sb["needs_sync"] = False
                        self.stats["syncs"] += 1
                    sb["t"].call(
                        "repl_apply",
                        {"epoch": epoch, "seq": seq,
                         "record": record},
                        timeout_s=self._timeout_s)
                    self.stats["applied"] += 1
                except TransportError:
                    # unreachable standby: repair with a full sync on
                    # next contact instead of replaying a gap
                    sb["needs_sync"] = True
                    self.stats["errors"] += 1
                except Exception:  # noqa: BLE001 - typed refusal
                    # a standby that claims a HIGHER epoch has been
                    # promoted: this primary is the zombie — stop
                    # streaming to it forever
                    sb["superseded"] = True
                    self.stats["superseded"] += 1


class StandbyMonitor:
    """Standby-side failure detector: promote the local standby once
    the primary has been continuously unreachable for
    ``promote_after_s`` (after having been seen alive at least
    once)."""

    def __init__(self, directory, primary: Transport, *,
                 promote_after_s: float = 3.0,
                 poll_s: float = 0.15,
                 time_fn: Callable[[], float] = time.monotonic):
        self._dir = directory
        self._primary = primary
        self.promote_after_s = float(promote_after_s)
        self.poll_s = poll_s
        self._now = time_fn
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="standby-monitor",
                                        daemon=True)
        self.stats = {"pings_ok": 0, "pings_failed": 0,
                      "promoted": 0}

    def start(self) -> "StandbyMonitor":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def _run(self) -> None:
        seen_alive = False
        last_ok: Optional[float] = None
        while not self._stop.is_set():
            if self._dir.role == PRIMARY:
                return              # promoted (by us or by hand)
            try:
                self._primary.call("ping", {}, timeout_s=0.5)
                self.stats["pings_ok"] += 1
                seen_alive = True
                last_ok = self._now()
            except Exception:  # noqa: BLE001 - any failure counts
                self.stats["pings_failed"] += 1
                down_for = (self._now() - last_ok
                            if last_ok is not None else 0.0)
                if seen_alive and down_for >= self.promote_after_s:
                    self._dir.rpc_promote(
                        reason=f"primary unreachable for "
                               f"{down_for:.2f}s")
                    self.stats["promoted"] += 1
                    return
            self._stop.wait(self.poll_s)


class FailoverDirectoryClient:
    """``DirectoryClient`` over an ORDERED endpoint list. Calls start
    at the last endpoint that answered; transport failures and typed
    ``NotPrimary`` advance to the next endpoint, every other typed
    error propagates (it IS the primary's answer)."""

    _METHODS = frozenset((
        "ping", "register", "renew", "deregister", "confirm_dead",
        "snapshot", "stats", "events", "role", "promote",
        "telemetry"))

    def __init__(self, transports: List[Transport],
                 timeout_s: float = 2.0):
        if not transports:
            raise ValueError("need at least one directory endpoint")
        self._clients = [DirectoryClient(t, timeout_s)
                         for t in transports]
        self._lock = threading.Lock()
        self._active = 0
        self.counters = {"calls": 0, "failovers": 0,
                         "not_primary_skips": 0,
                         "transport_skips": 0}

    @property
    def active_index(self) -> int:
        with self._lock:
            return self._active

    def __getattr__(self, name: str):
        if name not in FailoverDirectoryClient._METHODS:
            raise AttributeError(name)

        def _call(*args, **kwargs):
            return self._failover_call(name, args, kwargs)
        _call.__name__ = name
        return _call

    def _failover_call(self, name: str, args, kwargs):
        with self._lock:
            self.counters["calls"] += 1
            start = self._active
        n = len(self._clients)
        last_err: Optional[BaseException] = None
        for i in range(n):
            idx = (start + i) % n
            try:
                out = getattr(self._clients[idx], name)(*args,
                                                        **kwargs)
            except NotPrimary as e:
                last_err = e
                with self._lock:
                    self.counters["not_primary_skips"] += 1
                continue
            except TransportError as e:
                last_err = e
                with self._lock:
                    self.counters["transport_skips"] += 1
                continue
            with self._lock:
                if idx != self._active:
                    self._active = idx
                    self.counters["failovers"] += 1
            return out
        assert last_err is not None
        raise last_err
