"""Fleet-integrated capacity providers.

``FleetCapacityProvider`` closes the loop between the PR 6 serve-pool
autoscaler and the PR 11 process fleet: a ticket is a REPLICA ID, and
granting it means spawning a real ``ReplicaAgent`` OS process that
registers itself with the (replicated) directory and warms its
engine. ``ready()`` flips only after the agent printed ``READY`` —
i.e. after register + warm — so the autoscaler's harvest step adds a
member that can serve its first request immediately. ``release()``
retires the process; the health-gated drain (engine drained,
in-flight requests finished, lease deregistered, tombstone written)
already happened through ``FleetRouter.scale_down`` by the time the
autoscaler releases the ticket, so reaping here is just process
hygiene — and stays idempotent for the paths where it is not.

``LoopbackAgentProvider`` is the in-process twin used by
``llm.deployment(fleet=..., autoscale=...)``: provisioning constructs
and starts a loopback ``ReplicaAgent`` instead of forking one, with
an optional modeled delay so the ETA plumbing is exercised even
without process spawn latency.
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.autoscaler.node_provider import (CapacityUnavailable,
                                              ReplicaCapacityProvider)

__all__ = ["FleetCapacityProvider", "LoopbackAgentProvider"]


def _addr_pair(ep: Any) -> Tuple[str, int]:
    if isinstance(ep, str):
        host, _, port = ep.rpartition(":")
        return (host or "127.0.0.1", int(port))
    return (str(ep[0]), int(ep[1]))


class FleetCapacityProvider(ReplicaCapacityProvider):
    """Capacity == a warm agent process registered in the directory.

    ``request()`` forks ``python -m ray_tpu.serve.fleet.agent`` aimed
    at the ordered directory endpoint list and returns the replica id
    as the ticket; a waiter thread marks the ticket ready when the
    agent prints ``READY <port>`` (register + engine warm both behind
    it). ``eta_s`` is an EWMA of observed spawn->ready times minus
    elapsed, floored while pending so Retry-After never promises
    capacity that doesn't exist yet.
    """

    def __init__(self, directory_addrs: List[Any], *,
                 model: str = "fake",
                 token_delay_s: float = 0.002,
                 rid_prefix: str = "auto",
                 max_agents: Optional[int] = None,
                 spawn_timeout_s: float = 120.0,
                 initial_eta_s: float = 2.0,
                 extra_args: Optional[List[str]] = None,
                 env: Optional[Dict[str, str]] = None):
        self._dirs = [_addr_pair(e) for e in directory_addrs]
        if not self._dirs:
            raise ValueError("need at least one directory endpoint")
        self._model = model
        self._token_delay_s = token_delay_s
        self._prefix = rid_prefix
        self._max = max_agents
        self._spawn_timeout_s = spawn_timeout_s
        self._eta_ewma = float(initial_eta_s)
        self._extra_args = list(extra_args or [])
        self._env = env
        self._lock = threading.Lock()
        self._n = 0
        # ticket -> {"proc", "t_spawn", "ready", "port", "failed"}
        self._agents: Dict[str, Dict[str, Any]] = {}
        self.stats = {"spawned": 0, "ready": 0, "released": 0,
                      "denied": 0, "spawn_failures": 0}

    # ------------------------------------------------ provider ABC

    def request(self) -> str:
        with self._lock:
            if (self._max is not None
                    and len(self._agents) >= self._max):
                self.stats["denied"] += 1
                raise CapacityUnavailable(
                    f"agent ceiling {self._max} reached")
            self._n += 1
            rid = f"{self._prefix}-{self._n}"
            rec = self._spawn(rid)
            self._agents[rid] = rec
            self.stats["spawned"] += 1
        return rid

    def ready(self, ticket: str) -> bool:
        with self._lock:
            rec = self._agents.get(ticket)
        if rec is None:
            return False
        if rec["failed"]:
            # surface the dead spawn instead of pending forever: the
            # autoscaler treats a vanished ticket as never-ready and
            # its release() reaps what's left
            raise CapacityUnavailable(
                f"agent {ticket} died before READY")
        return bool(rec["ready"])

    def eta_s(self, ticket: str) -> float:
        with self._lock:
            rec = self._agents.get(ticket)
            ewma = self._eta_ewma
        if rec is None or rec["ready"]:
            return 0.0
        remaining = ewma - (time.monotonic() - rec["t_spawn"])
        # never promise sub-250ms while the process is still warming
        return max(remaining, 0.25)

    def release(self, ticket: str) -> None:
        with self._lock:
            rec = self._agents.pop(ticket, None)
        if rec is None:
            return
        self.stats["released"] += 1
        self._reap(rec)

    # ----------------------------------------------------- helpers

    def agent_port(self, ticket: str) -> Optional[int]:
        with self._lock:
            rec = self._agents.get(ticket)
        return rec["port"] if rec else None

    def live_count(self) -> int:
        with self._lock:
            return len(self._agents)

    def stop_all(self) -> None:
        with self._lock:
            recs = list(self._agents.values())
            self._agents.clear()
        for rec in recs:
            self._reap(rec)

    def _spawn(self, rid: str) -> Dict[str, Any]:
        cmd = [sys.executable, "-m", "ray_tpu.serve.fleet.agent",
               "--replica-id", rid, "--model", self._model,
               "--token-delay-s", str(self._token_delay_s)]
        for host, port in self._dirs:
            cmd += ["--directory", f"{host}:{port}"]
        cmd += self._extra_args
        env = dict(self._env if self._env is not None
                   else os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL,
                                env=env, text=True)
        rec = {"proc": proc, "t_spawn": time.monotonic(),
               "ready": False, "port": None, "failed": False}
        threading.Thread(target=self._wait_ready,
                         args=(rid, rec),
                         name=f"provider-wait-{rid}",
                         daemon=True).start()
        return rec

    def _wait_ready(self, rid: str, rec: Dict[str, Any]) -> None:
        deadline = rec["t_spawn"] + self._spawn_timeout_s
        out = rec["proc"].stdout
        while time.monotonic() < deadline:
            line = out.readline()
            if not line:            # EOF: process died pre-READY
                break
            if line.startswith("READY"):
                took = time.monotonic() - rec["t_spawn"]
                with self._lock:
                    rec["port"] = int(line.split()[1])
                    rec["ready"] = True
                    self._eta_ewma = (0.5 * self._eta_ewma
                                      + 0.5 * took)
                    self.stats["ready"] += 1
                # keep draining so the agent never blocks on a full
                # stdout pipe
                for _ in out:
                    pass
                return
        with self._lock:
            rec["failed"] = True
            self.stats["spawn_failures"] += 1

    @staticmethod
    def _reap(rec: Dict[str, Any]) -> None:
        proc = rec["proc"]
        if proc.poll() is None:
            # polite first: rpc_shutdown makes the agent deregister
            # cleanly if it's still serving (release() after a
            # scale_down drain finds it already deregistered — the
            # RPC is then a no-op shutdown)
            port = rec.get("port")
            if port:
                try:
                    from ray_tpu.serve.fleet.agent import AgentClient
                    from ray_tpu.serve.fleet.transport import (
                        SocketTransport)
                    AgentClient(SocketTransport(
                        ("127.0.0.1", port)), timeout_s=2.0
                    ).shutdown()
                except Exception:
                    pass
            try:
                proc.wait(timeout=3.0)
            except Exception:
                proc.terminate()
                try:
                    proc.wait(timeout=3.0)
                except Exception:
                    proc.kill()
                    proc.wait(timeout=3.0)
        try:
            if rec["proc"].stdout is not None:
                rec["proc"].stdout.close()
        except Exception:
            pass


class LoopbackAgentProvider(ReplicaCapacityProvider):
    """In-process provisioning for loopback fleets: 'spawning a host'
    is constructing + starting a ``ReplicaAgent`` around a fresh
    engine. ``agent_factory(replica_id)`` must build, start, AND make
    the agent routable (llm.py registers it in the transport map the
    router resolves addrs against). ``provision_delay_s`` models
    spin-up so the ETA/Retry-After plumbing is exercised."""

    def __init__(self, agent_factory: Callable[[str], Any], *,
                 provision_delay_s: float = 0.0,
                 rid_prefix: str = "auto",
                 max_agents: Optional[int] = None,
                 time_fn: Callable[[], float] = time.monotonic):
        self._factory = agent_factory
        self._delay = float(provision_delay_s)
        self._prefix = rid_prefix
        self._max = max_agents
        self._now = time_fn
        self._lock = threading.Lock()
        self._n = 0
        # ticket -> {"t_request", "agent" | None}
        self._tickets: Dict[str, Dict[str, Any]] = {}
        self.agents: Dict[str, Any] = {}
        self.stats = {"granted": 0, "built": 0, "released": 0,
                      "denied": 0}

    def request(self) -> str:
        with self._lock:
            if (self._max is not None
                    and len(self._tickets) >= self._max):
                self.stats["denied"] += 1
                raise CapacityUnavailable(
                    f"agent ceiling {self._max} reached")
            self._n += 1
            rid = f"{self._prefix}-{self._n}"
            self._tickets[rid] = {"t_request": self._now(),
                                  "agent": None}
            self.stats["granted"] += 1
        return rid

    def ready(self, ticket: str) -> bool:
        with self._lock:
            rec = self._tickets.get(ticket)
            if rec is None:
                return False
            if self._now() - rec["t_request"] < self._delay:
                return False
            build = rec["agent"] is None
            if build:
                rec["agent"] = "building"   # bar re-entry
        if build:
            agent = self._factory(ticket)
            with self._lock:
                rec["agent"] = agent
                self.agents[ticket] = agent
                self.stats["built"] += 1
        return True

    def eta_s(self, ticket: str) -> float:
        with self._lock:
            rec = self._tickets.get(ticket)
            if rec is None or rec["agent"] is not None:
                return 0.0
            return max(self._delay
                       - (self._now() - rec["t_request"]), 0.0)

    def release(self, ticket: str) -> None:
        with self._lock:
            rec = self._tickets.pop(ticket, None)
            agent = self.agents.pop(ticket, None)
        if rec is None:
            return
        self.stats["released"] += 1
        if agent is not None and agent != "building":
            try:
                agent.shutdown()
            except Exception:
                pass
