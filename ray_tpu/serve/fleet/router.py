"""FleetRouter: the pool's routing brain, re-homed over a transport.

The router owns NO engines. It reads the directory's membership
snapshot (lease-fenced members advertising prefix digests + load
reports), runs the SAME selection policy as ``EnginePool``
(``fleet.routing.select_candidate``: sticky → affinity/spill → P2C)
and speaks to the chosen ``ReplicaAgent`` through a transport with
per-call timeouts and exponential-backoff retries.

Failure semantics — the PR 5/9 recovery path, stretched across
processes:

- A **transport error** is only a DEATH CANDIDATE. The router can't
  distinguish a dead agent from a slow network, so it never judges
  alone: it asks the directory (``confirm_dead``), which answers
  from lease state. Alive → keep polling the same request (the agent
  is still running it). Dead → the standard at-most-once path: zero
  tokens delivered resubmits token-identically to another agent,
  anything else fails typed ``EngineShutdown``.
- **Streaming over RPC is cursor-polled**: submit returns a request
  id, ``poll(rid, cursor)`` returns the tokens past the cursor. A
  duplicated or retried poll re-reads instead of re-consuming, and a
  duplicated submit is deduplicated agent-side by the router-minted
  request key — so the transport may deliver at-least-once while the
  fleet serves at-most-once.
- Every **confirmed death dumps a flight bundle** (router events +
  the directory's verdict), so a cross-process kill is explained
  with the same evidence chain as an in-process one.
"""
from __future__ import annotations

import collections
import random
import threading
import time
from typing import (Any, Callable, Dict, Iterable, List, Optional,
                    Tuple)

from ray_tpu.serve import obs
from ray_tpu.serve.errors import (DeadlineExceeded, EngineDraining,
                                  EngineOverloaded, EngineShutdown,
                                  RequestCancelled)
from ray_tpu.serve.fleet import wire
from ray_tpu.serve.fleet.agent import AgentClient
from ray_tpu.serve.fleet.directory import DirectoryClient
from ray_tpu.serve.fleet.routing import (Candidate, ResubmitPolicy,
                                         select_candidate)
from ray_tpu.serve.fleet.transport import (Transport,
                                           TransportError)
from ray_tpu.serve.prefix_cache import path_hashes


class _Member:
    """Router-side view of one directory member (one incarnation:
    replica id + fence)."""

    __slots__ = ("replica_id", "addr", "generation", "fence",
                 "page_size", "report")

    def __init__(self, m: Dict[str, Any]):
        self.replica_id = m["replica_id"]
        self.addr = tuple(m["addr"])
        self.generation = int(m["generation"])
        self.fence = int(m["fence"])
        self.page_size = int(m.get("page_size") or 0)
        rpt = dict(m.get("load") or {})
        rpt["prefix_digest"] = frozenset(m.get("digest") or ())
        rpt.setdefault("outstanding_tokens", 0)
        rpt.setdefault("queue_depth", 0)
        rpt.setdefault("max_queued", None)
        rpt.setdefault("shed_retry_after_s", 0.05)
        # membership-level role backstops the load report: a member
        # that has never renewed still routes with the role it
        # registered under
        rpt.setdefault("role", m.get("role", "unified"))
        self.report = rpt

    @property
    def role(self) -> str:
        return self.report.get("role") or "unified"


class FleetRequestHandle(ResubmitPolicy):
    """Fleet-side request handle: the pool handle's surface
    (stream/result/cancel/done/error/ttft_s) implemented by polling
    the serving agent, with the shared at-most-once resubmit core."""

    def __init__(self, router: "FleetRouter", prompt: List[int],
                 max_new_tokens: int, deadline_s: Optional[float],
                 session_id: Optional[str],
                 trace_id: Optional[str]):
        super().__init__(prompt, max_new_tokens, deadline_s,
                         session_id, trace_id,
                         max_resubmits=router.max_resubmits)
        self._router = router
        self._member: Optional[_Member] = None
        self._rid: Optional[str] = None
        self._cursor = 0

    # ------------------------------------------------------- consuming

    def stream(self):
        r = self._router
        while True:
            death_cause: Optional[BaseException] = None
            patience = r.transport_patience_s
            t_trouble: Optional[float] = None
            try:
                while True:
                    try:
                        resp = r._agent(self._member).poll(
                            self._rid, cursor=self._cursor,
                            trace_id=self._trace_id,
                            timeout_s=r.call_timeout_s)
                    except TransportError as e:
                        # death candidate: the directory adjudicates
                        verdict = r._confirm_dead(self._member, e)
                        if verdict is True:
                            raise
                        now = time.monotonic()
                        if t_trouble is None:
                            t_trouble = now
                        if now - t_trouble > patience:
                            raise EngineShutdown(
                                f"agent {self._member.replica_id} "
                                f"unreachable for {patience:.1f}s "
                                f"and the directory cannot confirm "
                                f"its death") from e
                        # alive (or inconclusive): the agent may
                        # still be serving this request — re-poll
                        time.sleep(r.retry_backoff_s)
                        continue
                    t_trouble = None
                    if resp.get("error") is not None:
                        # tokens riding a failed response were never
                        # delivered — discard them so a zero-delivery
                        # request stays eligible for resubmission
                        wire.raise_error(resp["error"])
                    for tok in resp["tokens"]:
                        self._cursor += 1
                        self._note_token(tok)
                        yield tok
                    if resp.get("done"):
                        self._finished = True
                        return
                    time.sleep(r.poll_interval_s)
            except GeneratorExit:
                raise
            except (RequestCancelled, DeadlineExceeded) as e:
                self._fail(e)
                raise
            except (TransportError, EngineShutdown, EngineDraining,
                    wire.WireError) as e:
                # the serving incarnation is gone: confirmed dead
                # over the transport, fenced (AgentFenced is an
                # EngineDraining), force-killed (its raw error
                # crosses as a WireError), or rebuilt (unknown rid)
                death_cause = e
            except EngineOverloaded as e:
                self._fail(e)
                raise
            r._note_request_death(self._member, death_cause,
                                  trace_id=self._trace_id)
            if self._generated or self._cancelled:
                raise self._partial_stream_error(
                    self._member.replica_id,
                    death_cause) from death_cause
            self._resubmit(death_cause)

    # ------------------------------------------------------- lifecycle

    def cancel(self) -> bool:
        self._cancelled = True
        member, rid = self._member, self._rid
        if member is None or rid is None:
            return False
        try:
            return bool(self._router._agent(member).cancel(rid)
                        .get("cancelled"))
        except Exception:
            return False

    @property
    def replica_idx(self) -> Optional[str]:
        return (self._member.replica_id
                if self._member is not None else None)

    @property
    def replica_tag(self) -> Optional[str]:
        """``<replica_id>:<generation>`` of the serving agent — what
        the HTTP proxy echoes as ``X-Replica``."""
        if self._member is None:
            return None
        return f"{self._member.replica_id}:{self._member.generation}"

    # -------------------------------------------------------- internal

    def _resubmit(self, cause: BaseException) -> None:
        deadline = self._check_resubmit(cause)
        self._router._count_requeue(trace_id=self._trace_id)
        try:
            self._member, self._rid = self._router._submit_once(
                self._prompt, self._mnt, deadline, self._session_id,
                self._trace_id,
                exclude={self._member.replica_id})
            self._cursor = 0
        except BaseException as e:
            self._fail(e)
            raise

    def _attach(self, member: _Member, rid: str) -> None:
        self._member, self._rid = member, rid
        self._cursor = 0


class FleetRouter:
    """Routes requests to ReplicaAgents by the FleetDirectory's
    advertised state. Mirrors the EnginePool submit surface so the
    deployment layer can swap ``fleet=`` for ``num_engine_replicas``.

    Parameters
    ----------
    directory: DirectoryClient over any transport — or a
        ``replication.FailoverDirectoryClient`` over an ORDERED
        endpoint list (primary first, standbys after), which layers
        client-side failover UNDER the stale-snapshot fallback here:
        the router only falls back to its cache when every endpoint
        refused.
    transport_factory: ``f(addr_tuple) -> Transport`` building the
        client leg to one agent (loopback registry or socket dial);
        transports are cached per address.
    call_timeout_s / submit_retries / retry_backoff_s: per-RPC
        deadline and exponential-backoff retry (backoff doubles per
        attempt). Retried submits reuse the SAME request key, so the
        agent admits at most once however many frames arrive.
    max_resubmits: per-request cap on death-triggered resubmissions.
    snapshot_ttl_s: how long a directory snapshot is trusted before
        re-fetching; a failed refresh falls back to the stale cache
        (bounded staleness beats unavailability — this is what makes
        a directory restart invisible to in-flight clients).
    transport_patience_s: how long a request keeps re-polling an
        unreachable agent that the directory refuses to declare dead
        before failing typed.
    """

    def __init__(self, directory: DirectoryClient,
                 transport_factory: Callable[[Tuple], Transport], *,
                 seed: int = 0, call_timeout_s: float = 2.0,
                 submit_retries: int = 2,
                 retry_backoff_s: float = 0.02,
                 max_resubmits: int = 3,
                 snapshot_ttl_s: float = 0.05,
                 poll_interval_s: float = 0.004,
                 transport_patience_s: float = 10.0,
                 max_sticky_sessions: int = 4096,
                 flight_dir: Any = None):
        self._directory = directory
        self._transport_factory = transport_factory
        self._rng = random.Random(seed)
        self.call_timeout_s = float(call_timeout_s)
        self.submit_retries = int(submit_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.max_resubmits = int(max_resubmits)
        self.snapshot_ttl_s = float(snapshot_ttl_s)
        self.poll_interval_s = float(poll_interval_s)
        self.transport_patience_s = float(transport_patience_s)
        self._max_sticky = max_sticky_sessions
        self.flight_dir = flight_dir
        self._lock = threading.Lock()
        self._clients: Dict[Tuple, AgentClient] = {}
        self._snapshot_cache: Optional[Dict[str, _Member]] = None
        self._snapshot_t = 0.0
        self._sticky: "collections.OrderedDict[str, str]" = \
            collections.OrderedDict()
        self._dead_seen: set = set()
        self._seq = 0
        self._rseq = 0
        # autoscaler surface: idx -> replica_id for members the
        # autoscaler added (only these are scale_down candidates),
        # and the capacity-ETA hint hook PoolAutoscaler installs
        self._scaled: Dict[int, str] = {}
        self._scale_seq = 0
        self.capacity_hint_fn: Optional[Callable[[], float]] = None
        self.events = obs.EventLog(2048, name="router")
        # attached by TelemetryCollector.attach(): confirmed deaths
        # then pull a cluster-wide flight bundle, not just this
        # process's view
        self.telemetry_collector = None
        self.counters = {"routed": 0, "requeues": 0,
                         "deaths_confirmed": 0, "suspects": 0,
                         "confirm_inconclusive": 0,
                         "stale_snapshots": 0, "all_shed": 0,
                         "submit_retries": 0,
                         "snapshot_hits": 0, "snapshot_misses": 0,
                         "member_invalidations": 0,
                         "pull_hints": 0}
        self._stopped = False

    # --------------------------------------------------------- submit

    def submit(self, prompt_ids, max_new_tokens: int = 64,
               deadline_s: Optional[float] = None,
               session_id: Optional[str] = None,
               trace_id: Optional[str] = None) -> FleetRequestHandle:
        if self._stopped:
            raise EngineShutdown("fleet router stopped")
        prompt = list(prompt_ids)
        self.events.append(
            "submit", sid=session_id,
            data={"trace_id": trace_id,
                  "n_prompt": len(prompt),
                  "max_new_tokens": int(max_new_tokens)})
        h = FleetRequestHandle(self, prompt, max_new_tokens,
                               deadline_s, session_id, trace_id)
        member, rid = self._submit_once(prompt, max_new_tokens,
                                        deadline_s, session_id,
                                        trace_id, exclude=set())
        h._attach(member, rid)
        return h

    def _mint_key(self) -> str:
        with self._lock:
            self._seq += 1
            return f"req-{id(self):x}-{self._seq}"

    def _submit_once(self, prompt: List[int], max_new_tokens: int,
                     deadline_s: Optional[float],
                     session_id: Optional[str],
                     trace_id: Optional[str],
                     exclude: set) -> Tuple[_Member, str]:
        """Route + submit until one agent admits; typed aggregate
        failure when nothing can (the pool's ``_submit_once``, over
        a transport)."""
        exclude = set(exclude)
        shed: List[EngineOverloaded] = []
        while True:
            members = self._members(exclude)
            sticky_id = (self._sticky.get(session_id)
                         if session_id is not None else None)
            if sticky_id is not None:
                st = members.get(sticky_id)
                if st is not None and st.role == "prefill":
                    # a session must never pin to a prefill-only
                    # member: its decode stream lives elsewhere
                    with self._lock:
                        self._sticky.pop(session_id, None)
                    sticky_id = None
            cands = [Candidate(m.replica_id, m.report, m.page_size)
                     for m in members.values()]
            pick, decision = select_candidate(
                cands, prompt, sticky_key=sticky_id, rng=self._rng)
            if pick is None:
                hints = list(decision.get("hints", []))
                hints += [e.retry_after_s for e in shed]
                # provisioning honesty: when an autoscaler is mid
                # scale-up, its ETA joins the hint pool — the max
                # below then never invites a client back before the
                # capacity that would serve it can exist
                eta = self._capacity_eta()
                if hints:
                    if eta > 0:
                        hints.append(eta)
                    self.counters["all_shed"] += 1
                    err = EngineOverloaded(
                        f"all live agents shed (retry hints "
                        f"{sorted(set(round(h, 3) for h in hints))})",
                        retry_after_s=max(hints))
                    if shed:
                        raise err from shed[-1]
                    raise err
                err2 = EngineShutdown(
                    "no live agents in the fleet directory")
                # an honest hint: a lease period from now is the
                # soonest a restarted agent could re-advertise —
                # unless provisioning is pending and further out
                snap = self._snapshot_cache
                base = (self._lease_ttl_hint() if snap is not None
                        else 1.0)
                err2.retry_after_s = max(base, eta)
                raise err2
            member = members[pick.key]
            pull = self._pull_hint(prompt, member, members)
            key = self._mint_key()
            try:
                resp = self._call_with_retry(
                    lambda c, m=member, k=key, p=pull: c.submit(
                        k, prompt, max_new_tokens,
                        deadline_s=deadline_s, fence=m.fence,
                        pull=p, trace_id=trace_id,
                        timeout_s=self.call_timeout_s),
                    member)
            except TransportError as e:
                self._suspect(member, e)
                verdict = self._confirm_dead(member, e)
                if verdict is not True:
                    # transient or unconfirmable: evict only the
                    # suspect from the cache — one flaky agent must
                    # not force a directory round-trip for every
                    # unrelated routing decision
                    self._invalidate_member(member.replica_id)
                exclude.add(member.replica_id)
                continue
            except EngineOverloaded as e:
                shed.append(e)
                exclude.add(member.replica_id)
                continue
            except (EngineShutdown, EngineDraining) as e:
                # fenced / draining / stale fence: evict + reroute
                self._invalidate_member(member.replica_id)
                self._note_request_death(member, e,
                                         trace_id=trace_id,
                                         submit_side=True)
                exclude.add(member.replica_id)
                continue
            self._record_route(member, decision, session_id,
                               trace_id=trace_id)
            return member, resp["rid"]

    def _pull_hint(self, prompt: List[int], member: _Member,
                   members: Dict[str, _Member]
                   ) -> Optional[Dict[str, Any]]:
        """Global-prefix-cache routing: when some OTHER live member
        advertises a strictly longer contiguous prefix of this
        prompt than the chosen target does, attach a pull hint
        naming that donor — the target then PULLS the pages instead
        of recomputing them. Computed entirely from the snapshot's
        piggybacked digests (no extra directory round-trip on the
        submit path), and only a hint: a stale digest costs a failed
        pull that degrades to plain prefill."""
        Pg = member.page_size
        if Pg <= 0 or len(prompt) < Pg:
            return None
        chain = path_hashes(prompt, Pg)
        n_local = self._digest_cover(chain, member)
        best: Optional[_Member] = None
        best_n = n_local
        for rid, m in members.items():
            if rid == member.replica_id or m.page_size != Pg:
                continue
            n = self._digest_cover(chain, m)
            if n > best_n:
                best, best_n = m, n
        if best is None:
            return None
        with self._lock:
            self.counters["pull_hints"] += 1
        return {"hashes": chain[:best_n],
                "addr": list(best.addr),
                "replica_id": best.replica_id,
                "generation": best.generation}

    @staticmethod
    def _digest_cover(chain: List[int], m: _Member) -> int:
        have = m.report.get("prefix_digest") or frozenset()
        n = 0
        for h in chain:
            if h not in have:
                break
            n += 1
        return n

    def _call_with_retry(self, fn: Callable[[AgentClient], Any],
                         member: _Member) -> Any:
        """Per-call timeout + exponential backoff. Only transport
        errors retry (typed refusals are answers); the LAST error
        propagates for the caller's suspect path."""
        backoff = self.retry_backoff_s
        client = self._agent(member)
        last: Optional[TransportError] = None
        for attempt in range(self.submit_retries + 1):
            try:
                return fn(client)
            except TransportError as e:
                last = e
                if attempt < self.submit_retries:
                    self.counters["submit_retries"] += 1
                    time.sleep(backoff)
                    backoff *= 2
        raise last

    # ------------------------------------------------------ membership

    def _members(self, exclude: set) -> Dict[str, _Member]:
        snap = self._snapshot()
        return {rid: m for rid, m in snap.items()
                if rid not in exclude}

    def _snapshot(self) -> Dict[str, _Member]:
        now = time.monotonic()
        with self._lock:
            cached = self._snapshot_cache
            if (cached is not None
                    and now - self._snapshot_t < self.snapshot_ttl_s):
                self.counters["snapshot_hits"] += 1
                return cached
            self.counters["snapshot_misses"] += 1
        try:
            raw = self._directory.snapshot()
        except Exception:
            # directory unreachable (crashed / restarting): serve
            # from the stale cache — bounded staleness keeps clients
            # flowing through a directory restart
            self.counters["stale_snapshots"] += 1
            with self._lock:
                return dict(self._snapshot_cache or {})
        members: Dict[str, _Member] = {}
        for m in raw.get("members", []):
            if m.get("expired") or m.get("wedged"):
                continue
            if (m.get("load") or {}).get("state") == "fenced":
                continue
            mm = _Member(m)
            rpt = mm.report
            if rpt.get("stopped") or rpt.get("draining"):
                continue
            members[mm.replica_id] = mm
        self._lease_ttl = float(raw.get("lease_ttl_s", 1.0))
        with self._lock:
            self._snapshot_cache = members
            self._snapshot_t = now
        return members

    def _lease_ttl_hint(self) -> float:
        return getattr(self, "_lease_ttl", 1.0)

    def _invalidate_snapshot(self) -> None:
        with self._lock:
            self._snapshot_t = 0.0

    def _invalidate_member(self, replica_id: str) -> None:
        """Evict ONE member from the snapshot cache, leaving the
        rest trusted until the TTL: a single suspect doesn't cost
        everyone else a directory round-trip. The hit/miss counters
        prove the cache still earns its keep under churn."""
        with self._lock:
            self.counters["member_invalidations"] += 1
            cache = self._snapshot_cache
            if cache is not None and replica_id in cache:
                # copy-on-write: readers may be iterating the old map
                cache = dict(cache)
                del cache[replica_id]
                self._snapshot_cache = cache

    def _capacity_eta(self) -> float:
        fn = self.capacity_hint_fn
        if fn is None:
            return 0.0
        try:
            eta = float(fn() or 0.0)
        except Exception:
            return 0.0
        return eta if eta > 0 and eta != float("inf") else 0.0

    def _agent(self, member: _Member) -> AgentClient:
        with self._lock:
            c = self._clients.get(member.addr)
            if c is None:
                c = AgentClient(
                    self._transport_factory(member.addr),
                    timeout_s=self.call_timeout_s)
                self._clients[member.addr] = c
            return c

    # -------------------------------------------------- death handling

    def _suspect(self, member: _Member, cause: BaseException) -> None:
        self.counters["suspects"] += 1
        self.events.append("suspect", sid=member.replica_id,
                           data={"fence": member.fence,
                                 "cause": type(cause).__name__})

    def _confirm_dead(self, member: _Member,
                      cause: BaseException) -> Optional[bool]:
        """Ask the directory whether this incarnation is dead.
        True/False on a verdict, None when the directory itself is
        unreachable (inconclusive — NEVER grounds for a resubmit)."""
        try:
            v = self._directory.confirm_dead(member.replica_id,
                                             member.fence)
        except Exception:
            self.counters["confirm_inconclusive"] += 1
            return None
        if not v.get("dead"):
            return False
        self._on_confirmed_death(member, v, cause)
        return True

    def _on_confirmed_death(self, member: _Member,
                            verdict: Dict[str, Any],
                            cause: BaseException) -> None:
        tag = (member.replica_id, member.fence)
        with self._lock:
            if tag in self._dead_seen:
                return
            self._dead_seen.add(tag)
            self.counters["deaths_confirmed"] += 1
            for k in [k for k, v in self._sticky.items()
                      if v == member.replica_id]:
                del self._sticky[k]
        self._invalidate_member(member.replica_id)
        self.events.append(
            "member_dead", sid=member.replica_id,
            data={"fence": member.fence,
                  "generation": member.generation,
                  "reason": verdict.get("reason"),
                  "cause": type(cause).__name__})
        if self.flight_dir:
            try:
                obs.dump_flight_bundle(
                    self.flight_dir,
                    f"agent-dead-{member.replica_id}", pool=self,
                    extra={"replica_id": member.replica_id,
                           "fence": member.fence,
                           "generation": member.generation,
                           "verdict": verdict,
                           "cause": repr(cause)})
            except Exception:
                pass
        if self.telemetry_collector is not None:
            try:
                self.telemetry_collector.on_fault(
                    f"agent-dead-{member.replica_id}",
                    trigger={"kind": "confirmed_death",
                             "replica_id": member.replica_id,
                             "generation": member.generation,
                             "fence": member.fence,
                             "cause": type(cause).__name__})
            except Exception:
                pass

    def _note_request_death(self, member: _Member,
                            cause: BaseException,
                            trace_id: Optional[str] = None,
                            submit_side: bool = False) -> None:
        self.events.append(
            "replica_death", sid=member.replica_id,
            data={"cause": type(cause).__name__,
                  "submit_side": submit_side,
                  "trace_id": trace_id})

    def _count_requeue(self, trace_id: Optional[str] = None) -> None:
        with self._lock:
            self.counters["requeues"] += 1
        self.events.append("resubmit",
                           data={"trace_id": trace_id}
                           if trace_id is not None else None)

    def _record_route(self, member: _Member,
                      decision: Dict[str, Any],
                      session_id: Optional[str],
                      trace_id: Optional[str] = None) -> None:
        self.events.append(
            "route", sid=member.replica_id,
            data={"kind": decision["kind"],
                  "pages": decision.get("pages", 0),
                  "spilled": bool(decision.get("spilled")),
                  "trace_id": trace_id})
        with self._lock:
            self.counters["routed"] += 1
            if session_id is not None and member.role != "prefill":
                self._sticky[session_id] = member.replica_id
                self._sticky.move_to_end(session_id)
                while len(self._sticky) > self._max_sticky:
                    self._sticky.popitem(last=False)

    # ---------------------------------------------------- aggregation

    @property
    def stats(self) -> Dict[str, int]:
        """Engine-surface counter mirror — deployment/bench code
        that does ``dict(engine.stats)`` works on a router too."""
        with self._lock:
            return dict(self.counters)

    def load_report(self) -> Dict[str, Any]:
        """Fleet-aggregate load report (the pool's shape, summed
        over live members' advertised reports). Carries every key
        ``PoolAutoscaler`` senses on, so the autoscaler can drive a
        fleet exactly like an ``EnginePool``."""
        members = self._snapshot()
        out: Dict[str, Any] = {
            "replicas": len(members),
            "healthy_replicas": len(members),
            "free_slots": 0, "total_slots": 0,
            "queue_depth": 0, "outstanding_tokens": 0,
            "shed_total": 0,
            "ttft_ewma_s": None,
            "draining": False, "stopped": not members}
        for m in members.values():
            for k in ("free_slots", "total_slots", "queue_depth",
                      "outstanding_tokens", "shed_total"):
                v = m.report.get(k)
                if isinstance(v, (int, float)):
                    out[k] += v
            ttft = m.report.get("ttft_ewma_s")
            if isinstance(ttft, (int, float)):
                out["ttft_ewma_s"] = max(out["ttft_ewma_s"] or 0.0,
                                         float(ttft))
        with self._lock:
            out["shed_total"] += self.counters["all_shed"]
        return out

    # -------------------------------------------- autoscaler surface

    def active_count(self) -> int:
        """Live (routable) members — the autoscaler's notion of the
        current scale."""
        return len(self._snapshot())

    def add_replica_for_ticket(self, ticket: str) -> int:
        """Harvest hook: the agent behind ``ticket`` (its replica id)
        registered itself with the directory, so 'adding' it to the
        fleet is just refreshing the routing view and remembering it
        as an autoscaler-owned scale-down candidate."""
        with self._lock:
            self._scale_seq += 1
            idx = self._scale_seq
            self._scaled[idx] = str(ticket)
        self._invalidate_snapshot()
        self.events.append("scale_up", sid=str(ticket),
                           data={"idx": idx})
        return idx

    def add_replica(self) -> int:
        return self.add_replica_for_ticket("")

    def scale_down(self, k: int = 1,
                   timeout_s: float = 15.0,
                   rids: Optional[Iterable[str]] = None) -> List[int]:
        """Retire ``k`` autoscaler-added agents: health-gated drain
        (in-flight requests finish), lease retirement + tombstone
        (the agent deregisters itself inside ``rpc_drain``), routing
        eviction. Victims are the least-loaded scaled members; the
        static floor is never touched. ``rids`` restricts the
        candidate set (a caller retiring a SPECIFIC provisioned
        agent, not just 'any k'). Returns the retired idxs — the
        autoscaler releases their provider tickets (which reaps the
        OS processes) from these."""
        members = self._snapshot()
        allow = None if rids is None else {str(r) for r in rids}
        with self._lock:
            cands = [(idx, rid) for idx, rid in self._scaled.items()
                     if rid in members
                     and (allow is None or rid in allow)]
        cands.sort(key=lambda pair: (
            members[pair[1]].report.get("outstanding_tokens", 0),
            members[pair[1]].report.get("queue_depth", 0),
            pair[0]))
        retired: List[int] = []
        for idx, rid in cands[:max(0, int(k))]:
            m = members[rid]
            try:
                self._agent(m).drain(timeout_s=timeout_s)
            except Exception:  # noqa: BLE001 - a dead agent is
                pass           # already retired; the tombstone wins
            with self._lock:
                self._scaled.pop(idx, None)
            self._invalidate_member(rid)
            self.events.append("scale_down", sid=rid,
                               data={"idx": idx})
            retired.append(idx)
        return retired

    def pool_stats(self) -> Dict[str, Any]:
        """Router-side observability block (named pool_stats so
        ``obs.dump_flight_bundle(pool=router)`` records it)."""
        with self._lock:
            out = {"counters": dict(self.counters),
                   "sticky_sessions": len(self._sticky),
                   "dead_seen": len(self._dead_seen)}
        try:
            out["directory"] = self._directory.stats()
        except Exception:
            out["directory"] = None
        return out

    def member_stats(self) -> Dict[str, Any]:
        """Per-agent stats over the transport (loopback fleets use
        this for deployment-level aggregation)."""
        out = {}
        for rid, m in self._snapshot().items():
            try:
                out[rid] = self._agent(m).stats()
            except Exception:
                out[rid] = None
        return out

    def shutdown(self) -> None:
        self._stopped = True
        with self._lock:
            clients = list(self._clients.values())
        for c in clients:
            try:
                c._t.close()
            except Exception:
                pass
