"""Distributed fleet control plane: the EnginePool split into three
processes behind a pluggable transport.

- ``directory``: FleetDirectory — membership keyed by replica id +
  generation, lease-based liveness, monotonic fencing tokens.
- ``agent``: ReplicaAgent — wraps one LLMEngine per host, renews its
  lease, self-fences when the lease lapses.
- ``router``: FleetRouter — routes by advertised digests/load, treats
  transport errors as replica death candidates (suspect →
  directory-confirmed dead → token-identical resubmit).
- ``transport``: the seam — in-process loopback, length-prefixed
  JSON-over-socket, and a seeded fault-injecting wrapper.
- ``routing``: the selection + resubmit core shared with EnginePool.
- ``wire``: the JSON wire schema (envelopes carry trace ids so
  ``obs.request_phases()`` still reconstructs end-to-end).
- ``wal``: crash-durable directory state — append-only checksummed
  WAL + atomic-rename snapshots (PR 7 torn-file discipline).
- ``replication``: hot-standby delta streaming, standby promotion
  with epoch-folded fencing, and the ordered-endpoint failover
  client routers/agents hold.
- ``provider``: fleet-integrated autoscaler capacity — tickets that
  spawn/retire real agent processes (or loopback agents in-process).
- ``telemetry``: the fleet observability plane — cursor-resumed
  cross-process scrape, NTP-style clock alignment, trace stitching,
  and cluster flight bundles.

Attribute access is lazy (PEP 562): ``engine_pool`` imports
``fleet.routing`` for the shared core, while ``fleet.agent`` imports
``watchdog`` which imports ``engine_pool`` — eager re-exports here
would close that cycle mid-import.
"""
import importlib

_EXPORTS = {
    "FleetDirectory": "directory", "DirectoryClient": "directory",
    "ReplicaAgent": "agent", "AgentClient": "agent",
    "ScriptedEngine": "agent",
    "FleetRouter": "router",
    "LoopbackTransport": "transport", "SocketTransport": "transport",
    "SocketServer": "transport", "FaultyTransport": "transport",
    "TransportError": "transport", "TransportTimeout": "transport",
    "AgentFenced": "wire", "StaleFencingToken": "wire",
    "UnknownMember": "wire", "NotPrimary": "wire",
    "DirectoryWAL": "wal",
    "Replicator": "replication", "StandbyMonitor": "replication",
    "FailoverDirectoryClient": "replication",
    "FleetCapacityProvider": "provider",
    "LoopbackAgentProvider": "provider",
    "TelemetryCollector": "telemetry",
    "ClockOffsetEstimator": "telemetry",
    "merge_prometheus_texts": "telemetry",
    "load_cluster_bundle": "telemetry",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(f"{__name__}.{mod}"),
                   name)
