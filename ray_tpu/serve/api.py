"""Serve public API.

Capability parity with the reference's @serve.deployment / serve.run
(python/ray/serve/api.py:250,428).
"""
from __future__ import annotations

import functools
import inspect
import time
from typing import Any, Callable, Dict, Optional, Union

import ray_tpu
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.controller import (CONTROLLER_NAME, Controller,
                                      get_or_create_controller)
# Typed request-lifecycle errors (serve/errors.py): part of the serve
# API surface — clients branch on them, the proxy maps them to HTTP
# statuses (429/504/503/499), and they import without jax.
from ray_tpu.serve.errors import (DeadlineExceeded,  # noqa: F401
                                  EngineDraining, EngineOverloaded,
                                  EngineShutdown, RequestCancelled,
                                  RequestError)
from ray_tpu.serve.router import (DeploymentHandle, clear_handle_cache,
                                  get_or_create_handle)


class Deployment:
    def __init__(self, target: Union[type, Callable], name: str,
                 config: DeploymentConfig):
        self._target = target
        self.name = name
        self.config = config
        self._init_args: tuple = ()
        self._init_kwargs: Dict[str, Any] = {}

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[int] = None,
                max_ongoing_requests: Optional[int] = None,
                autoscaling_config: Optional[AutoscalingConfig] = None,
                ray_actor_options: Optional[Dict[str, Any]] = None,
                mesh: Optional[Dict[str, int]] = None,
                user_config: Optional[Dict[str, Any]] = None
                ) -> "Deployment":
        import dataclasses
        cfg = dataclasses.replace(self.config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if autoscaling_config is not None:
            cfg.autoscaling_config = autoscaling_config
        if ray_actor_options is not None:
            cfg.ray_actor_options = ray_actor_options
        if mesh is not None:
            cfg.mesh = mesh
        if user_config is not None:
            cfg.user_config = user_config
        d = Deployment(self._target, name or self.name, cfg)
        d._init_args = self._init_args
        d._init_kwargs = self._init_kwargs
        return d

    def bind(self, *args, **kwargs) -> "Deployment":
        d = Deployment(self._target, self.name, self.config)
        d._init_args = args
        d._init_kwargs = kwargs
        return d

    def _as_class(self) -> type:
        if inspect.isclass(self._target):
            return self._target
        fn = self._target

        class _FnWrapper:
            def __call__(self, *a, **k):
                return fn(*a, **k)
        _FnWrapper.__name__ = getattr(fn, "__name__", "fn")
        return _FnWrapper


def deployment(_target=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 8,
               autoscaling_config: Optional[AutoscalingConfig] = None,
               ray_actor_options: Optional[Dict[str, Any]] = None,
               mesh: Optional[Dict[str, int]] = None,
               user_config: Optional[Dict[str, Any]] = None):
    """``@serve.deployment`` decorator for classes or functions."""

    def wrap(target):
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            autoscaling_config=autoscaling_config,
            ray_actor_options=ray_actor_options,
            mesh=mesh,
            user_config=user_config)
        return Deployment(
            target, name or getattr(target, "__name__", "deployment"),
            cfg)

    if _target is not None:
        return wrap(_target)
    return wrap


def _deploy_one(dep: Deployment, controller, deployed: set,
                timeout_s: float) -> DeploymentHandle:
    """Deploy `dep`, first recursively deploying any bound Deployment
    found in its init args and substituting its handle — model
    composition via deployment graphs (reference: serve deployment
    graphs built on python/ray/dag, deployment_graph.py)."""
    def resolve(v):
        if isinstance(v, Deployment):
            return _deploy_one(v, controller, deployed, timeout_s)
        if isinstance(v, dict):
            return {k: resolve(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            out = [resolve(x) for x in v]
            return tuple(out) if isinstance(v, tuple) else out
        return v

    if dep.name in deployed:
        return get_or_create_handle(dep.name)
    deployed.add(dep.name)
    init_args = tuple(resolve(a) for a in dep._init_args)
    init_kwargs = {k: resolve(v) for k, v in dep._init_kwargs.items()}
    ray_tpu.get(controller.deploy.remote(
        dep.name, dep._as_class(), init_args, init_kwargs, dep.config))
    if timeout_s > 0:     # timeout_s<=0 means "don't wait for readiness"
        deadline = time.time() + timeout_s
        while not ray_tpu.get(controller.ready.remote(dep.name)):
            if time.time() > deadline:
                raise TimeoutError(
                    f"Deployment {dep.name!r} not ready in {timeout_s}s")
            time.sleep(0.02)
    return get_or_create_handle(dep.name)


def run(dep: Deployment, *, wait_for_ready: bool = True,
        timeout_s: float = 60.0) -> DeploymentHandle:
    """Deploy (or update) a deployment — or a whole deployment graph:
    bound Deployments appearing in init args are deployed recursively
    and replaced by their handles. Returns the root handle."""
    from ray_tpu._private.usage_stats import record_library_usage
    record_library_usage("serve")
    controller = get_or_create_controller()
    return _deploy_one(dep, controller, set(),
                       timeout_s if wait_for_ready else 0.0)


def get_handle(name: str) -> DeploymentHandle:
    return get_or_create_handle(name)


def get_deployment_handle(deployment_name: str,
                          app_name: str = "") -> DeploymentHandle:
    """Exact-shape parity with the reference's accessor; this runtime
    has a single default app, so app_name is accepted and ignored."""
    return get_handle(deployment_name)


def get_deployment(name: str) -> Dict[str, Any]:
    info = ray_tpu.get(
        get_or_create_controller().list_deployments.remote())
    if name not in info:
        raise ValueError(f"No deployment named {name!r}")
    return info[name]


def list_deployments() -> Dict[str, Any]:
    return ray_tpu.get(
        get_or_create_controller().list_deployments.remote())


def status() -> Dict[str, Any]:
    """Deployment + replica status summary (reference: serve.status()
    schema — application/deployment statuses)."""
    info = list_deployments()
    return {
        "deployments": {
            name: {
                "status": ("HEALTHY"
                           if d["num_replicas"] >= max(1, d["target"])
                           else "UPDATING"),
                **d,
            } for name, d in info.items()},
    }


def delete(name: str):
    """Remove one deployment (reference: serve.delete)."""
    controller = get_or_create_controller()
    ray_tpu.get(controller.delete_deployment.remote(name))


def shutdown():
    clear_handle_cache()
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    try:
        ray_tpu.get(controller.shutdown.remote(), timeout=10)
    except Exception:
        pass
    ray_tpu.kill(controller)
