"""Radix-tree prefix KV cache: share identical prompt prefixes' KV
pages across requests, ref-counted, LRU-evicted.

Under the realistic "millions of users" load most prompts share a long
system-prompt / few-shot prefix, yet the engine used to prefill every
request from token 0 — burning the round's prefill budget recomputing
identical KV. Ray's object store gets its leverage from immutable
shared data plus reference counting (the plasma design); this module
applies the same idea to KV pages:

- A host-side RADIX TREE keyed on token-id chunks of exactly
  ``page_size`` tokens (page-aligned nodes) maps prompt prefixes to
  physical page ids in the paged KV pool (models/kv_cache.py). One
  node owns one page; a path root->node spells a prefix whose KV is
  fully resident.
- Each cached page carries a REFERENCE COUNT of the live slots whose
  page tables point at it. Pages with refcount > 0 are never returned
  to the free list and never evicted — a reader's gather can always
  trust its page table.
- Cache-held pages with refcount == 0 form the LRU EVICTION POOL:
  when the allocator runs dry, ``evict(n)`` frees least-recently-
  matched leaf pages back to the BlockAllocator, so cache residency
  costs nothing under pressure — admission reclaims it before the
  engine ever preempts a live sequence.

Copy-on-write discipline (enforced by the engine, stated here because
the tree's correctness depends on it): pool pages are donated to
jitted calls and updated in place, so a shared page must NEVER be a
scatter target. Matching is page-granular, which keeps every shared
page strictly behind the owning slot's write frontier
(``slot.prefilled``/``pos``); a fully-cached prompt copies its one
boundary page into a private page before re-prefilling the final
token (the model still needs the last position's logits to sample).

Quantized pools (``kv_dtype="int8"``) change NOTHING here: this tree
deals only in page NUMBERS, and the per-page scale tensors live in
device arrays indexed by the same physical page id — a cached page's
scale is refcounted/evicted/realloc'd implicitly with its id, the
engine's jitted COW copy duplicates the scale column alongside the
page (``_build_copy_page``), and a freed page's stale scale is
zeroed on first reuse by ``paged_append``'s reset-on-offset-0 rule.

Metrics (util/metrics.py Counter/Gauge, served by the dashboard's
Prometheus exposition): hit/miss tokens, evictions, resident pages.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

HIT_TOKENS = "serve_prefix_cache_hit_tokens"
MISS_TOKENS = "serve_prefix_cache_miss_tokens"
EVICTIONS = "serve_prefix_cache_evictions"
CACHED_PAGES = "serve_prefix_cache_pages"

_METRICS: Optional[dict] = None


def _metrics() -> dict:
    """Lazy module-level metric singletons, re-created if a test's
    ``clear_registry()`` dropped them (Metric registration is global
    per process; values live on the instances)."""
    global _METRICS
    from ray_tpu.util import metrics
    if (_METRICS is None
            or metrics.registry().get(HIT_TOKENS)
            is not _METRICS["hit_tokens"]):
        _METRICS = {
            "hit_tokens": metrics.Counter(
                HIT_TOKENS,
                "Prompt tokens admitted from cached prefix KV "
                "(prefill skipped)"),
            "miss_tokens": metrics.Counter(
                MISS_TOKENS, "Prompt tokens prefilled from scratch"),
            "evictions": metrics.Counter(
                EVICTIONS, "Cached pages reclaimed under pressure"),
            "cached_pages": metrics.Gauge(
                CACHED_PAGES, "KV pages currently held by the prefix "
                "cache (refcount-0 ones are evictable)"),
        }
    return _METRICS


def _child_hash(parent_hash: int, chunk: Tuple[int, ...]) -> int:
    """Rolling path hash: a node's hash commits to the full token path
    root->node, not just its own chunk. ``hash`` over int tuples is
    deterministic (ints hash to themselves; tuple combining does not
    use PYTHONHASHSEED), so two trees that cached the same prefix
    compute the same value."""
    return hash((parent_hash, chunk))


def path_hashes(tokens: Sequence[int], page_size: int) -> List[int]:
    """The rolling path hashes a prompt WOULD occupy in a tree with
    this ``page_size`` — one per full page chunk, in prefix order.

    This is the routing-side mirror of the tree's per-node ``phash``:
    an EnginePool hashes an incoming prompt once, then compares
    against each replica's ``digest()`` set to find which replica
    holds the longest cached prefix, without shipping token ids or
    walking a remote tree."""
    h = 0
    out: List[int] = []
    for i in range(0, (len(tokens) // page_size) * page_size,
                   page_size):
        h = _child_hash(h, tuple(int(t) for t in
                                 tokens[i:i + page_size]))
        out.append(h)
    return out


class _Node:
    """One radix-tree node = one full page of tokens = one physical
    page. ``chunk`` is the ``page_size``-tuple of token ids this edge
    spells; ``tick`` is the LRU stamp (monotonic counter, not wall
    clock, so tests are deterministic); ``phash`` is the rolling path
    hash (see ``path_hashes``) used for pool prefix-affinity digests."""

    __slots__ = ("chunk", "page", "parent", "children", "tick",
                 "phash")

    def __init__(self, chunk: Tuple[int, ...], page: int,
                 parent: "_Node", tick: int):
        self.chunk = chunk
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.tick = tick
        self.phash = (_child_hash(parent.phash, chunk)
                      if parent is not None else 0)


class PrefixCache:
    """Radix-tree prefix index over the engine's ``BlockAllocator``.

    The cache never allocates pages itself: sequences prefill into
    pages they own, and ``insert`` transfers ownership of finished
    full prompt pages to the tree instead of freeing them. ``match``
    hands those pages back out as shared, read-only prefixes. All
    calls happen under the engine lock (single scheduler thread plus
    ``submit``), so no internal locking.
    """

    def __init__(self, alloc, page_size: int):
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.alloc = alloc
        self.Pg = page_size
        self._root = _Node((), 0, None, 0)
        self._nodes: Dict[int, _Node] = {}     # page id -> node
        self._ref: Dict[int, int] = {}         # page id -> live slots
        self._tick = 0
        # plain-int mirrors of the process metrics so bench artifacts
        # and engine.stats read per-engine numbers
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.evictions = 0

    # ----------------------------------------------------------- read

    @property
    def cached_pages(self) -> int:
        return len(self._nodes)

    def ref_of(self, page: int) -> int:
        return self._ref.get(page, 0)

    def evictable_pages(self) -> int:
        """Refcount-0 resident pages (the reclaimable pool)."""
        return sum(1 for p in self._nodes if self._ref.get(p, 0) == 0)

    def digest(self, limit: Optional[int] = None) -> frozenset:
        """Compact content fingerprint of the tree: the set of rolling
        path hashes of resident nodes. An EnginePool intersects a
        prompt's ``path_hashes`` with this set to compute, per replica,
        how many leading pages are already cached — the longest-prefix
        affinity signal. O(nodes); no token ids leave the replica.

        ``limit`` caps the advertisement so fleet load reports stay
        bounded as the cache grows. The truncation is PREFIX-CLOSED:
        affinity matching walks a prompt's path hashes root-first and
        stops at the first absence, so advertising a deep node without
        its ancestors would make the whole path invisible. Whole
        root->node paths are kept, chosen deepest-first (longest
        reusable prefix wins) then hottest-first (LRU tick) among
        equal depths; a path that no longer fits the budget is skipped
        in favor of shorter ones, so the budget is filled with the
        longest/hottest prefixes that fit."""
        if limit is None or len(self._nodes) <= limit:
            return frozenset(n.phash for n in self._nodes.values())
        if limit <= 0:
            return frozenset()
        depth: Dict[int, int] = {}
        for n in self._nodes.values():
            d, node = 0, n
            while node is not self._root:
                node = node.parent
                d += 1
            depth[n.page] = d
        ranked = sorted(self._nodes.values(),
                        key=lambda n: (-depth[n.page], -n.tick))
        keep: set = set()
        for n in ranked:
            if len(keep) >= limit:
                break
            path = []
            node = n
            while node is not self._root and node.phash not in keep:
                path.append(node.phash)
                node = node.parent
            if len(keep) + len(path) > limit:
                continue           # doesn't fit: try shorter paths
            keep.update(path)
        return frozenset(keep)

    def match_hashes(self, hashes: Sequence[int]
                     ) -> Tuple[List[int], int]:
        """Longest resident run of ``hashes`` (rolling path hashes in
        prefix order, see ``path_hashes``), walking the tree WITHOUT
        token ids — the donor side of a cross-replica KV pull resolves
        a requester's advertised-digest match to physical pages with
        only hashes on the wire.

        Returns ``(pages, n_hashes_matched)``. Every returned page's
        refcount is INCREMENTED (this is the transfer-lifetime PIN:
        pinned pages can never be evicted mid-pull); the caller owes
        one ``release`` per page. Matched nodes are LRU-touched."""
        self._tick += 1
        node = self._root
        pages: List[int] = []
        for h in hashes:
            child = None
            for c in node.children.values():
                if c.phash == h:
                    child = c
                    break
            if child is None:
                break
            child.tick = self._tick
            pages.append(child.page)
            node = child
        for p in pages:
            self._ref[p] = self._ref.get(p, 0) + 1
        return pages, len(pages)

    def _chunks(self, tokens: Sequence[int]):
        for i in range(0, (len(tokens) // self.Pg) * self.Pg, self.Pg):
            yield tuple(int(t) for t in tokens[i:i + self.Pg])

    def match(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached prefix of ``tokens``, page-granular.

        Returns ``(pages, n_tokens)`` with ``n_tokens == len(pages) *
        page_size``. Every returned page's refcount is INCREMENTED —
        the caller owes a ``release`` (directly, or via ``insert`` at
        retirement) for each. Matched nodes are LRU-touched. Stats are
        NOT counted here: the engine may cap the match (fully-cached
        prompt) and reports what it actually skipped via ``account``.
        """
        self._tick += 1
        node = self._root
        pages: List[int] = []
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None:
                break
            child.tick = self._tick
            pages.append(child.page)
            node = child
        for p in pages:
            self._ref[p] = self._ref.get(p, 0) + 1
        return pages, len(pages) * self.Pg

    def account(self, hit_tokens: int, miss_tokens: int) -> None:
        """Record one admission's hit/miss token split (what the
        engine actually skipped vs computed)."""
        self.hit_tokens += hit_tokens
        self.miss_tokens += miss_tokens
        m = _metrics()
        if hit_tokens:
            m["hit_tokens"].inc(hit_tokens)
        if miss_tokens:
            m["miss_tokens"].inc(miss_tokens)

    # ---------------------------------------------------------- write

    def release(self, pages: Sequence[int]) -> None:
        """Drop one reference per page (slot retired or preempted).
        Pages reaching refcount 0 STAY resident — they just become
        evictable. Never frees to the allocator."""
        for p in pages:
            if p not in self._nodes:
                raise RuntimeError(
                    f"release of page {p} not held by the prefix "
                    f"cache")
            r = self._ref.get(p, 0)
            if r <= 0:
                raise RuntimeError(
                    f"refcount underflow on cached page {p}")
            self._ref[p] = r - 1

    def insert(self, tokens: Sequence[int], pages: Sequence[int],
               n_shared: int) -> None:
        """Insert a finished sequence's full prompt pages into the
        tree, transferring ownership (the engine must NOT free them).

        tokens: the fully-prefilled prompt; only its
            ``len(tokens) // page_size`` full pages are insertable.
        pages: the physical pages holding those chunks, logical order
            (``len(pages)`` == number of full prompt pages).
        n_shared: leading pages that came from ``match`` at admission
            — for those the tree already holds the SAME page and this
            call releases the sequence's reference. Private pages
            beyond that are donated to the tree, unless an identical
            chunk landed first (two concurrent misses on the same
            prefix): the duplicate page is freed to the allocator and
            the incumbent kept.
        """
        self._tick += 1
        node = self._root
        for i, chunk in enumerate(self._chunks(tokens)):
            if i >= len(pages):
                break
            page = int(pages[i])
            child = node.children.get(chunk)
            if child is None:
                if i < n_shared:
                    raise RuntimeError(
                        f"shared page {page} vanished from the tree "
                        f"while referenced (chunk {i})")
                child = _Node(chunk, page, node, self._tick)
                node.children[chunk] = child
                self._nodes[page] = child
                self._ref.setdefault(page, 0)
            else:
                child.tick = self._tick
                if child.page == page:
                    # our reference came from match(): hand it back
                    self.release([page])
                else:
                    # duplicate compute of the same prefix: keep the
                    # incumbent (other readers may hold refs on it),
                    # recycle ours
                    self.alloc.free([page])
            node = child
        _metrics()["cached_pages"].set(len(self._nodes))

    def evict(self, n: int) -> int:
        """Free up to ``n`` least-recently-used refcount-0 LEAF pages
        back to the allocator (leaf-first keeps every resident path
        rooted — a parentless child could never be matched). Returns
        how many pages were actually freed."""
        freed = 0
        while freed < n:
            victim = None
            for page, node in self._nodes.items():
                if self._ref.get(page, 0) == 0 and not node.children:
                    if victim is None or node.tick < victim.tick:
                        victim = node
            if victim is None:
                break
            del victim.parent.children[victim.chunk]
            del self._nodes[victim.page]
            self._ref.pop(victim.page, None)
            self.alloc.free([victim.page])
            freed += 1
            self.evictions += 1
        if freed:
            m = _metrics()
            m["evictions"].inc(freed)
            m["cached_pages"].set(len(self._nodes))
        return freed

    def clear(self) -> int:
        """Evict everything evictable (tests/teardown)."""
        return self.evict(len(self._nodes))

    # ----------------------------------------------------- diagnostics

    def stats(self) -> dict:
        total = self.hit_tokens + self.miss_tokens
        return {
            "hit_tokens": self.hit_tokens,
            "miss_tokens": self.miss_tokens,
            "hit_rate": round(self.hit_tokens / total, 4) if total
            else 0.0,
            "evictions": self.evictions,
            "cached_pages": self.cached_pages,
            "evictable_pages": self.evictable_pages(),
        }

    def check_invariants(self) -> None:
        """Structural sanity for tests: page<->node bijection, no
        cached page on the allocator free list, refcounts sane, tree
        reachability."""
        for page, node in self._nodes.items():
            assert node.page == page, (node.page, page)
            assert node.parent.children.get(node.chunk) is node
            assert node.phash == _child_hash(node.parent.phash,
                                             node.chunk)
            assert self._ref.get(page, 0) >= 0
            assert page not in getattr(self.alloc, "_free_set", ()), \
                f"cached page {page} is also on the free list"
        stack = [self._root]
        seen = 0
        while stack:
            nd = stack.pop()
            for child in nd.children.values():
                assert self._nodes.get(child.page) is child
                seen += 1
                stack.append(child)
        assert seen == len(self._nodes), (seen, len(self._nodes))
