"""Seeded chaos for the serving pool: schedules, injection, gating.

The serving stack's availability story is spread over four PRs —
replica death + token-identical resubmit (engine pool), SLO-driven
scaling against a capacity provider that can say no (pool
autoscaler), typed degradation with honest Retry-After (errors /
proxy), and hang -> death escalation (watchdog). Each piece has its
own tests; this module is the ADVERSARIAL proof that they compose: a
deterministic fault campaign fired against a live multi-replica pool
under trace load, mirroring the training side's harness
(train/chaos.py) at the serving layer's seams (serve/faults.py).

Schedule kinds (``make_schedule`` always plans >= 1 of each):

==================  ====================================================
kind                what fires
==================  ====================================================
``kill``            whole-replica death at the next scheduling round
                    (``FaultInjector.kill_replica``) — the pool's
                    resubmit drill
``hang``            one replica's scheduler wedges INSIDE a round,
                    holding the engine lock, making zero progress but
                    answering lock-free probes — the failure only the
                    watchdog's progress deadline catches. Backed by a
                    releasable ``hang`` plan, so teardown can unwedge
                    the zombie and prove the generation fence
``slow``            a bounded delay at the step site — progress
                    continues, the heartbeat keeps moving, and the
                    watchdog must NOT fire (false-positive control)
``readback``        an injected per-rider readback fault — contained
                    by the engine (culprit fails typed, innocents
                    requeue), never escalating to replica death
``stockout``        the capacity provider denies requests for a
                    window (``CapacityUnavailable``) while the
                    autoscaler may be mid-scale-up
``kill_during_drain``  a replica is killed WHILE a scale-down drain
                    is in flight on it — the three-way race between
                    drain, death, and resubmission
==================  ====================================================

Events are keyed to campaign wall time (serving has no global step
counter); the SCHEDULE — order, kinds, targets, windows — is
deterministic from the seed, which is what the artifact stamps and
the schema gate checks.
"""
from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import (CapacityUnavailable,
                                              ReplicaCapacityProvider)
from ray_tpu.serve.engine_pool import HEALTHY

logger = logging.getLogger(__name__)

KINDS = ("kill", "hang", "slow", "readback", "stockout",
         "kill_during_drain")

# Cross-process campaign (tools/chaos_serve.py --fleet): replicas are
# real OS processes behind the fleet control plane (serve/fleet/).
#
# ==================   =================================================
# ``kill_agent``       SIGKILL one replica-agent PROCESS — the router
#                      must suspect, get the death directory-confirmed
#                      (lease expiry), and resubmit token-identically
# ``partition``        one agent's network drops both ways (inbound
#                      gate + outbound renew skip) — it must SELF-FENCE
#                      when its lease lapses so it can never
#                      double-serve a request the router resubmitted
# ``directory_restart``  SIGKILL the current primary and restart it
#                      on the same port + data dir — membership
#                      recovers from the WAL/snapshot (not from agent
#                      re-advertisement); clients must not notice
# ``primary_kill``     SIGKILL the primary PERMANENTLY — the hot
#                      standby must promote (epoch bump folded into
#                      the fence counter so no token regresses) and a
#                      post-failover canary must complete
#                      token-identically through the promoted
#                      directory
# ``torn_wal_restart``  SIGKILL the current primary, append a TORN
#                      half-record to its WAL (the crash-mid-write
#                      case), restart — the tail must be detected and
#                      truncated, never replayed, and membership must
#                      still recover
# ``autoscale_churn``  a FleetCapacityProvider spawns a real agent
#                      process mid-campaign (spawn -> register ->
#                      warm), the router harvests it, then drains +
#                      retires it while load continues
# ==================   =================================================
FLEET_KINDS = ("kill_agent", "partition", "directory_restart",
               "primary_kill", "torn_wal_restart", "autoscale_churn")


@dataclasses.dataclass
class ChaosEvent:
    """One planned fault. Fires when the campaign clock reaches
    ``at_s`` (seconds since ``ChaosInjector.start``)."""
    kind: str
    at_s: float
    duration_s: float = 0.5        # slow: delay; stockout: window
    fired: bool = False
    fired_at_s: Optional[float] = None
    target_idx: Optional[int] = None   # replica hit (filled at fire)

    def as_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "at_s": round(self.at_s, 4),
                "duration_s": self.duration_s, "fired": self.fired,
                "fired_at_s": (round(self.fired_at_s, 4)
                               if self.fired_at_s is not None
                               else None),
                "target_idx": self.target_idx}


def make_schedule(seed: int, duration_s: float, kinds=KINDS,
                  extra: int = 0, slow_s: float = 0.2,
                  stockout_s: float = 0.5) -> List[ChaosEvent]:
    """Deterministic schedule: >= 1 event of every kind in ``kinds``
    plus ``extra`` more, spread over (0.1, 0.8) * ``duration_s`` so
    nothing fires before the load warms up or too late to observe
    recovery before the campaign ends. Same seed => identical
    schedule."""
    n = len(kinds) + extra
    lo, hi = 0.1 * duration_s, 0.8 * duration_s
    span = (hi - lo) / n
    if span <= 0:
        raise ValueError(
            f"duration_s={duration_s} too small for {n} events")
    rng = random.Random(seed)
    ordered = list(kinds) + [rng.choice(list(kinds))
                             for _ in range(extra)]
    rng.shuffle(ordered)
    events = []
    for i, kind in enumerate(ordered):
        at = lo + i * span + rng.random() * span * 0.5
        dur = slow_s if kind == "slow" else stockout_s
        events.append(ChaosEvent(kind=kind, at_s=at, duration_s=dur))
    return events


def make_fleet_schedule(seed: int, duration_s: float,
                        kinds=FLEET_KINDS, extra: int = 0,
                        partition_s: float = 1.0
                        ) -> List[ChaosEvent]:
    """Deterministic cross-process schedule: same contract as
    ``make_schedule`` (>= 1 of each kind, seeded order and timing)
    with ``partition_s`` as the partition window."""
    base = make_schedule(seed, duration_s, kinds=kinds, extra=extra)
    for ev in base:
        if ev.kind == "partition":
            ev.duration_s = partition_s
    return base


class FleetChaosInjector:
    """Watcher thread firing a fleet schedule through harness-owned
    fault operations. The harness owns the OS processes, so injection
    is delegated: ``ops[kind](event, rng) -> target-or-None`` performs
    the fault and returns a target label (recorded in the log) or
    None when it can't fire yet (the event retries next tick, same as
    ``ChaosInjector``)."""

    def __init__(self, schedule: List[ChaosEvent],
                 ops: Dict[str, Callable[[ChaosEvent, random.Random],
                                         Optional[str]]], *,
                 seed: int = 0, poll_s: float = 0.02,
                 time_fn: Callable[[], float] = time.monotonic):
        self.schedule = sorted(schedule, key=lambda e: e.at_s)
        self.ops = ops
        self.poll_s = poll_s
        self._time = time_fn
        self._rng = random.Random(seed)
        self.log: List[Dict[str, Any]] = []
        self._t0: Optional[float] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="fleet-chaos",
                                        daemon=True)

    def start(self) -> "FleetChaosInjector":
        self._t0 = self._time()
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30)

    def done(self) -> bool:
        return all(e.fired for e in self.schedule)

    def injected_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.schedule:
            if e.fired:
                out[e.kind] = out.get(e.kind, 0) + 1
        return out

    def _run(self) -> None:
        while not self._stop.is_set():
            elapsed = self._time() - self._t0
            for ev in self.schedule:
                if ev.fired or elapsed < ev.at_s:
                    continue
                op = self.ops.get(ev.kind)
                try:
                    target = op(ev, self._rng) if op else None
                except Exception as e:  # noqa: BLE001 - keep firing
                    logger.warning("fleet chaos %s failed: %s",
                                   ev.kind, e)
                    target = None
                if target is not None:
                    ev.fired = True
                    ev.fired_at_s = elapsed
                    d = ev.as_dict()
                    d["target"] = target
                    self.log.append(d)
                break
            if self.done():
                return
            time.sleep(self.poll_s)


class StockoutCapacityProvider(ReplicaCapacityProvider):
    """Capacity provider wrapper with an injectable stockout window:
    while the window is open every ``request`` raises
    ``CapacityUnavailable`` (and is counted), after it the base
    provider answers again. The chaos ``stockout`` event opens the
    window mid-campaign, so an autoscaler scale-up attempt lands on a
    denial exactly like a real provisioning stockout."""

    def __init__(self, base: ReplicaCapacityProvider,
                 time_fn: Callable[[], float] = time.monotonic):
        self._base = base
        self._time = time_fn
        self._lock = threading.Lock()
        self._until = 0.0
        self.denied = 0

    def set_stockout(self, duration_s: float) -> None:
        with self._lock:
            self._until = self._time() + duration_s

    def stocked_out(self) -> bool:
        with self._lock:
            return self._time() < self._until

    def request(self) -> str:
        with self._lock:
            if self._time() < self._until:
                self.denied += 1
                raise CapacityUnavailable(
                    "injected capacity stockout")
        return self._base.request()

    def ready(self, ticket: str) -> bool:
        return self._base.ready(ticket)

    def eta_s(self, ticket: str) -> float:
        return self._base.eta_s(ticket)

    def release(self, ticket: str) -> None:
        self._base.release(ticket)


def release_all_hangs(pool) -> int:
    """Release every ``hang`` plan on every replica engine's injector
    (current engines only — callers tracking corpse engines from
    before a rebuild release those via their own registry). Call in
    EVERY chaos/teardown path."""
    n = 0
    for eng in pool.engines():
        inj = getattr(eng, "_injector", None)
        if inj is not None:
            n += inj.release_all()
    return n


class ChaosInjector:
    """Watcher thread firing a schedule against a live EnginePool.

    Targets are chosen seeded among the HEALTHY replicas at fire
    time; each replica engine must carry a ``FaultInjector``
    (``LLMEngine(fault_injector=...)`` — the harness factory wires
    one per build, including rebuilds). ``provider`` (a
    ``StockoutCapacityProvider``) backs stockout events;
    ``kill_during_drain`` needs >= 2 healthy replicas at fire time.

    ``stop()`` joins the watcher AND every drain thread it spawned,
    then releases every hang — a campaign can never leak a wedged
    thread past teardown.
    """

    def __init__(self, pool, schedule: List[ChaosEvent], *,
                 seed: int = 0,
                 provider: Optional[StockoutCapacityProvider] = None,
                 drain_timeout_s: float = 5.0,
                 poll_s: float = 0.01,
                 time_fn: Callable[[], float] = time.monotonic):
        self.pool = pool
        self.schedule = sorted(schedule, key=lambda e: e.at_s)
        self.provider = provider
        self.drain_timeout_s = drain_timeout_s
        self.poll_s = poll_s
        self._time = time_fn
        self._rng = random.Random(seed)
        self.log: List[Dict[str, Any]] = []
        self._t0: Optional[float] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="serve-chaos",
                                        daemon=True)
        self._drains: List[threading.Thread] = []
        # replicas retired/killed through the drain race — the
        # harness asserts resubmits never landed on them
        self.drain_victims: List[int] = []

    def start(self) -> "ChaosInjector":
        self._t0 = self._time()
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30)
        for t in self._drains:
            t.join(timeout=self.drain_timeout_s + 30)
        release_all_hangs(self.pool)

    def injected_counts(self) -> Dict[str, int]:
        out = {k: 0 for k in KINDS}
        for e in self.schedule:
            if e.fired:
                out[e.kind] = out.get(e.kind, 0) + 1
        return out

    # ------------------------------------------------------------ loop

    def _run(self) -> None:
        while not self._stop.is_set():
            elapsed = self._time() - self._t0
            for ev in self.schedule:
                if ev.fired or elapsed < ev.at_s:
                    continue
                if self._fire(ev):
                    ev.fired = True
                    ev.fired_at_s = elapsed
                    self.log.append(ev.as_dict())
                break   # at most one event per tick (fired or not:
                        # an unfireable event retries next tick
                        # without starving the ones behind it)
            if all(e.fired for e in self.schedule):
                return
            time.sleep(self.poll_s)

    def _pick_healthy(self, min_healthy: int = 1,
                      clean_only: bool = False):
        """A seeded pick among HEALTHY replicas whose engine carries
        an injector (None when fewer than ``min_healthy`` qualify —
        the event retries next tick). Prefers replicas with no
        pending unfired plans so concurrent events don't stack on
        one victim (a pending kill on the hang target would take the
        replica down BEFORE the wedge); ``clean_only`` makes that a
        requirement instead of a preference."""
        with self.pool._lock:
            reps = [r for r in self.pool._replicas
                    if r.state == HEALTHY
                    and getattr(r.engine, "_injector", None)
                    is not None]
        if len(reps) < min_healthy:
            return None
        clean = [r for r in reps
                 if all(p.fired >= p.times
                        for p in r.engine._injector.plans)]
        if clean_only and not clean:
            return None
        return self._rng.choice(clean or reps)

    def _fire(self, ev: ChaosEvent) -> bool:
        try:
            if ev.kind == "kill":
                return self._fire_kill(ev)
            if ev.kind == "hang":
                return self._fire_hang(ev)
            if ev.kind == "slow":
                return self._fire_slow(ev)
            if ev.kind == "readback":
                return self._fire_readback(ev)
            if ev.kind == "stockout":
                return self._fire_stockout(ev)
            if ev.kind == "kill_during_drain":
                return self._fire_kill_during_drain(ev)
        except Exception as e:  # noqa: BLE001 - injection must not die
            logger.warning("chaos event %s failed to fire: %s",
                           ev.kind, e)
            return False
        return False

    def _fire_kill(self, ev: ChaosEvent) -> bool:
        rep = self._pick_healthy()
        if rep is None:
            return False
        ev.target_idx = rep.idx
        rep.engine._injector.kill_replica()
        return True

    def _fire_hang(self, ev: ChaosEvent) -> bool:
        # Wedge at the step site: the scheduler thread parks holding
        # the engine lock with its heartbeat already touched this
        # round — from here on the age only grows, which is exactly
        # the signal the watchdog escalates on.
        rep = self._pick_healthy(clean_only=True)
        if rep is None:
            return False
        ev.target_idx = rep.idx
        rep.engine._injector.hang("step")
        return True

    def _fire_slow(self, ev: ChaosEvent) -> bool:
        # A delay below the suspect threshold: rounds keep completing,
        # the heartbeat keeps moving — long-but-moving must NOT wedge.
        rep = self._pick_healthy()
        if rep is None:
            return False
        ev.target_idx = rep.idx
        rep.engine._injector.slow("step", ev.duration_s)
        return True

    def _fire_readback(self, ev: ChaosEvent) -> bool:
        rep = self._pick_healthy()
        if rep is None:
            return False
        ev.target_idx = rep.idx
        # The engine CONTAINS a readback fault: exactly the culprit
        # request fails — with this exception — and innocents requeue.
        # The stable message is the harness's marker for telling the
        # planned casualty apart from an actually-lost request.
        rep.engine._injector.inject(
            "readback",
            exc=RuntimeError("injected readback fault"))
        return True

    def _fire_stockout(self, ev: ChaosEvent) -> bool:
        if self.provider is None:
            return False
        self.provider.set_stockout(ev.duration_s)
        # Probe the denial so the stockout is OBSERVED even when the
        # autoscaler happens not to scale up inside the window (the
        # provider-level denial is the real event; an autoscaler
        # request in the window lands on the same refusal).
        try:
            ticket = self.provider.request()
        except CapacityUnavailable:
            pass
        else:   # pragma: no cover - window must be open here
            self.provider.release(ticket)
            return False
        return True

    def _fire_kill_during_drain(self, ev: ChaosEvent) -> bool:
        # The three-way race: start a scale-down drain on a replica,
        # then kill it mid-drain. The pool must (a) fail/resubmit its
        # in-flight work under the at-most-once rule, (b) never route
        # a resubmit back to the draining corpse, (c) quiesce
        # leak-free.
        rep = self._pick_healthy(min_healthy=2, clean_only=True)
        if rep is None:
            return False
        ev.target_idx = rep.idx
        self.drain_victims.append(rep.idx)

        def _drain():
            try:
                self.pool.retire(rep.idx,
                                 timeout_s=self.drain_timeout_s)
            except Exception:   # noqa: BLE001 - last-healthy guard,
                pass            # pool shut down, etc.

        t = threading.Thread(target=_drain,
                             name=f"chaos-drain-{rep.idx}",
                             daemon=True)
        t.start()
        self._drains.append(t)
        # kill lands while the drain is (very likely) still in
        # flight; if the drain already finished, the kill plan hits a
        # stopped engine and simply never fires — still a valid race
        # outcome, and the event counts as fired either way
        time.sleep(min(0.05, self.drain_timeout_s / 4))
        rep.engine._injector.kill_replica()
        return True
