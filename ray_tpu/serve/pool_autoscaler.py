"""SLO-driven closed-loop autoscaling for the EnginePool.

PR 5 made replica count a knob; this module makes it a CONTROL
VARIABLE. The pool already exposes everything a controller needs —
``load_report()`` aggregates queue depth, shed totals, free-slot
fraction, and a worst-replica TTFT EWMA; ``add_replica`` grows the
fleet; ``scale_down`` retires replicas through the health-gated drain
path — and the autoscaler closes the loop against a declarative SLO
policy, the Ray-paper architecture (demand-driven scaling as part of
the runtime control plane) applied to the serving tier.

Control loop (``tick()``, normally run by a background thread):

1. **Harvest capacity**: poll pending provisioning tickets; every
   ticket that became ready turns into a live replica via
   ``pool.add_replica()``.
2. **Sense**: read ``pool.load_report()``; derive the shed RATE from
   the monotone shed counter; compute queue-per-replica and the
   free-slot fraction.
3. **Decide** (``_decide``): scale UP when any pressure signal fires
   (queue per replica above ``queue_high``, any shedding, TTFT EWMA
   over the SLO, or scarce free slots with a backlog); scale DOWN
   only when the pool has been COMPLETELY quiet (no queue, no sheds,
   ample free slots) for ``idle_stable_s`` continuously; otherwise
   HOLD. The gap between ``queue_high`` and
   ``queue_low`` plus the idle-stability window is the hysteresis
   band that keeps a noisy workload from flapping the fleet.
4. **Act**, clamped by min/max bounds and per-direction cooldowns:
   scale-up REQUESTS capacity from a pluggable
   ``ReplicaCapacityProvider`` (a TPU slice takes real minutes to
   provision — the replica joins on a later tick, step 1); scale-down
   retires the least-loaded replicas via ``pool.scale_down`` — the
   SAME drain path as a rolling restart, so in-flight requests finish
   token-identically and nothing is lost.

Retry-After honesty: while capacity is provisioning the autoscaler
installs ``capacity_eta_s`` as the pool's ``capacity_hint_fn``, so an
all-shed ``EngineOverloaded`` carries a hint covering the remaining
provisioning time — a shed NEVER invites the client back before the
capacity that would serve it exists.

Failure interplay: replica deaths are the pool's problem
(auto-restart with exponential backoff, PR 6 satellite); the
autoscaler only sees the resulting capacity dip through the same load
signals and responds by provisioning more. A crash-looped DEGRADED
replica therefore gets replaced by economics, not by special-casing.
"""
from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import (CapacityUnavailable,
                                              ImmediateCapacityProvider,
                                              ReplicaCapacityProvider)

SCALE_UP = "serve_pool_scale_up_total"
SCALE_DOWN = "serve_pool_scale_down_total"
SCALE_HOLD = "serve_pool_scale_hold_total"
TARGET_REPLICAS = "serve_pool_target_replicas"

_METRICS: Optional[dict] = None


def _metrics() -> dict:
    """Lazy module-level metric singletons, re-created if a test's
    ``clear_registry()`` dropped them (same pattern as the engine,
    pool, and prefix-cache modules)."""
    global _METRICS
    from ray_tpu.util import metrics
    if (_METRICS is None
            or metrics.registry().get(SCALE_UP)
            is not _METRICS["scale_up"]):
        _METRICS = {
            "scale_up": metrics.Counter(
                SCALE_UP, "Autoscaler scale-up decisions (replicas "
                "requested)"),
            "scale_down": metrics.Counter(
                SCALE_DOWN, "Autoscaler scale-down decisions "
                "(replicas retired)"),
            "scale_hold": metrics.Counter(
                SCALE_HOLD, "Autoscaler ticks that held the current "
                "size (inside the hysteresis band or cooldown)"),
            "target_replicas": metrics.Gauge(
                TARGET_REPLICAS, "Autoscaler's current target "
                "replica count (live + provisioning)"),
        }
    return _METRICS


@dataclasses.dataclass
class SLOPolicy:
    """Declarative scaling policy: WHAT the operator wants (bounds,
    SLO, stability) — the controller derives the when/how.

    Scale-up triggers (any one fires):
    - ``queue_high``: admission-queue depth per healthy replica.
    - ``shed_rate_high``: sheds/second; the default 0.0 means ANY
      shedding is an SLO event worth paying chips for.
    - ``ttft_slo_s``: worst-replica TTFT EWMA budget (None = no TTFT
      term).
    - ``itl_slo_s``: worst-replica inter-token-latency EWMA budget
      (None = no ITL term). The decode side of a disaggregated pool
      scales on THIS plus free slots — its TTFT is the handoff, not
      client experience.
    - ``free_slot_frac_low``: free-slot fraction floor — scarce slots
      WITH a backlog means saturation is imminent.

    Scale-down requires ALL of: zero queue, zero shed rate, free-slot
    fraction at/above ``free_slot_frac_high`` — sustained for
    ``idle_stable_s``. TTFT is deliberately NOT part of the idle
    test: the EWMA is a lagging indicator, and an otherwise-idle pool
    must not be pinned at size by the memory of a past slow burst
    (a breach still forces scale-UP). Queue per replica between
    ``queue_low`` and ``queue_high`` always holds (hysteresis band).

    ``cooldown_up_s``/``cooldown_down_s`` are per-direction refractory
    periods; down is much longer because adding capacity is urgent
    while removing it is merely thrifty.
    """
    min_replicas: int = 1
    max_replicas: int = 4
    queue_high: float = 2.0
    queue_low: float = 0.5
    shed_rate_high: float = 0.0
    ttft_slo_s: Optional[float] = None
    itl_slo_s: Optional[float] = None
    free_slot_frac_low: float = 0.1
    free_slot_frac_high: float = 0.6
    idle_stable_s: float = 5.0
    cooldown_up_s: float = 2.0
    cooldown_down_s: float = 10.0
    scale_up_step: int = 1
    scale_down_step: int = 1
    drain_timeout_s: float = 30.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                "max_replicas must be >= min_replicas")
        if self.queue_low > self.queue_high:
            raise ValueError("queue_low must be <= queue_high "
                             "(hysteresis band)")


class PoolAutoscaler:
    """Drives ``pool`` toward its SLO under ``policy`` using capacity
    from ``provider``. ``time_fn`` is injectable so policy tests run
    on a fake clock. Construction attaches the scaler to the pool
    (``pool_stats()`` grows an ``autoscale`` block; all-shed
    Retry-After hints start covering provisioning ETAs) but does NOT
    start the loop — call ``run()`` or drive ``tick()`` manually.
    """

    def __init__(self, pool, policy: Optional[SLOPolicy] = None,
                 provider: Optional[ReplicaCapacityProvider] = None,
                 *, time_fn: Callable[[], float] = time.monotonic):
        self.pool = pool
        self.policy = policy or SLOPolicy()
        self.provider = provider or ImmediateCapacityProvider()
        self._time = time_fn
        self._lock = threading.Lock()
        self._pending: List[str] = []        # provisioning tickets
        self._ticket_by_idx: Dict[int, str] = {}
        self._last_up = -math.inf
        self._last_down = -math.inf
        self._idle_since: Optional[float] = None
        self._last_shed_total: Optional[int] = None
        self._last_tick_t: Optional[float] = None
        self.counts: Dict[str, int] = {
            "ticks": 0, "scale_ups": 0, "scale_downs": 0,
            "holds": 0, "denied": 0, "replicas_added": 0,
            "replicas_retired": 0}
        self.last_decision: str = "none"
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # timeline of (t, active, target) at decision points — the
        # bench samples this for the replica-count artifact
        self.timeline: List[tuple] = []
        pool._autoscaler = self
        pool.capacity_hint_fn = self.capacity_eta_s

    # -------------------------------------------------------- sensing

    def capacity_eta_s(self) -> float:
        """Remaining ETA until ALL in-flight provisioning lands (0
        when nothing is pending). The pool folds this into all-shed
        Retry-After hints."""
        with self._lock:
            pending = list(self._pending)
        eta = 0.0
        for t in pending:
            try:
                eta = max(eta, self.provider.eta_s(t))
            except Exception:
                pass
        return eta

    def target_replicas(self) -> int:
        """Live capacity plus capacity already on order."""
        with self._lock:
            pending = len(self._pending)
        return self.pool.active_count() + pending

    def signals(self) -> Dict[str, Any]:
        """One sensed sample: the pool aggregate plus derived rates.
        ``shed_rate`` comes from the monotone ``shed_total`` counter
        differenced against the previous tick (clamped at 0: a
        retiring replica takes its counter with it)."""
        now = self._time()
        rpt = self.pool.load_report()
        healthy = max(1, rpt.get("healthy_replicas", 1))
        total_slots = rpt.get("total_slots", 0)
        free_frac = (rpt.get("free_slots", 0) / total_slots
                     if total_slots else 1.0)
        shed_total = rpt.get("shed_total", 0)
        dt = (now - self._last_tick_t
              if self._last_tick_t is not None else None)
        if self._last_shed_total is None or not dt or dt <= 0:
            shed_rate = 0.0
        else:
            shed_rate = max(0, shed_total
                            - self._last_shed_total) / dt
        self._last_shed_total = shed_total
        self._last_tick_t = now
        return {
            "now": now,
            "stopped": rpt.get("stopped", False),
            "queue_depth": rpt.get("queue_depth", 0),
            "queue_per_replica":
                rpt.get("queue_depth", 0) / healthy,
            "shed_rate": shed_rate,
            "free_slot_frac": free_frac,
            "ttft_ewma_s": rpt.get("ttft_ewma_s"),
            "itl_ewma_s": rpt.get("itl_ewma_s"),
            "role": rpt.get("role"),
            "healthy_replicas": rpt.get("healthy_replicas", 0),
        }

    # ------------------------------------------------------- deciding

    def _decide(self, sig: Dict[str, Any]) -> str:
        """Pure policy: map one sensed sample to "up" | "down" |
        "hold" (bounds/cooldowns are applied by ``tick``, not here,
        so tests can probe the policy surface directly)."""
        p = self.policy
        ttft = sig.get("ttft_ewma_s")
        ttft_breach = (p.ttft_slo_s is not None and ttft is not None
                       and ttft > p.ttft_slo_s)
        itl = sig.get("itl_ewma_s")
        itl_breach = (p.itl_slo_s is not None and itl is not None
                      and itl > p.itl_slo_s)
        pressure = (sig["queue_per_replica"] > p.queue_high
                    or sig["shed_rate"] > p.shed_rate_high
                    or ttft_breach
                    or itl_breach
                    or (sig["free_slot_frac"] < p.free_slot_frac_low
                        and sig["queue_depth"] > 0))
        if pressure:
            self._idle_since = None
            return "up"
        # TTFT deliberately absent here: a breach already returned
        # "up" above, and the EWMA is a LAGGING indicator — an idle
        # pool (no queue, no sheds, ample slots) must not be pinned
        # at size by the memory of a past slow burst
        idle = (sig["queue_depth"] == 0
                and sig["shed_rate"] == 0
                and sig["free_slot_frac"] >= p.free_slot_frac_high
                and sig["queue_per_replica"] <= p.queue_low)
        if not idle:
            # inside the hysteresis band: neither pressured enough to
            # pay for chips nor quiet enough to give them back
            self._idle_since = None
            return "hold"
        if self._idle_since is None:
            self._idle_since = sig["now"]
        if sig["now"] - self._idle_since < p.idle_stable_s:
            return "hold"
        return "down"

    # --------------------------------------------------------- acting

    def tick(self) -> str:
        """One control iteration (harvest -> sense -> decide -> act).
        Returns the ACTED decision: "up"/"down" when capacity moved
        or was ordered, else "hold"."""
        if getattr(self.pool, "_stopped", False):
            return "hold"
        self._harvest_ready()
        sig = self.signals()
        if sig["stopped"]:
            return "hold"
        p = self.policy
        now = sig["now"]
        decision = self._decide(sig)
        target = self.target_replicas()
        acted = "hold"
        if decision == "up":
            if (now - self._last_up >= p.cooldown_up_s
                    and target < p.max_replicas):
                k = min(p.scale_up_step, p.max_replicas - target)
                requested = self._request_capacity(k)
                if requested:
                    self._last_up = now
                    with self._lock:
                        self.counts["scale_ups"] += requested
                    _metrics()["scale_up"].inc(requested)
                    acted = "up"
        elif decision == "down":
            with self._lock:
                pending = len(self._pending)
            if (pending == 0
                    and now - self._last_down >= p.cooldown_down_s
                    and target > p.min_replicas):
                k = min(p.scale_down_step, target - p.min_replicas)
                retired = self.pool.scale_down(
                    k, timeout_s=p.drain_timeout_s)
                if retired:
                    self._last_down = now
                    self._idle_since = None
                    self._release(retired)
                    with self._lock:
                        self.counts["scale_downs"] += len(retired)
                        self.counts["replicas_retired"] += \
                            len(retired)
                    _metrics()["scale_down"].inc(len(retired))
                    acted = "down"
        if acted == "hold":
            with self._lock:
                self.counts["holds"] += 1
            _metrics()["scale_hold"].inc()
        with self._lock:
            self.counts["ticks"] += 1
            self.last_decision = acted
        target = self.target_replicas()
        _metrics()["target_replicas"].set(target)
        self.timeline.append((now, self.pool.active_count(), target))
        if acted != "hold":
            # capacity moved: put the decision on the pool's event
            # timeline (holds would drown the ring at one per tick)
            log = getattr(self.pool, "events", None)
            if log is not None:
                log.append("autoscale", data={
                    "decision": acted, "target": target,
                    "queue_per_replica":
                        round(sig["queue_per_replica"], 4),
                    "shed_rate": round(sig["shed_rate"], 4),
                    "free_slot_frac":
                        round(sig["free_slot_frac"], 4)})
        return acted

    def _harvest_ready(self) -> None:
        """Turn every provisioned ticket into a live replica."""
        with self._lock:
            pending = list(self._pending)
        for ticket in pending:
            try:
                if not self.provider.ready(ticket):
                    continue
            except Exception:
                continue
            # pools that track WHICH agent a ticket provisioned (the
            # fleet router: ticket == replica id) take it here, so
            # scale-down can retire exactly that agent later; the
            # EnginePool builds anonymous replicas and ignores it
            add_for = getattr(self.pool, "add_replica_for_ticket",
                              None)
            idx = (add_for(ticket) if add_for is not None
                   else self.pool.add_replica())
            with self._lock:
                self._pending.remove(ticket)
                self._ticket_by_idx[idx] = ticket
                self.counts["replicas_added"] += 1

    def _request_capacity(self, k: int) -> int:
        """Order ``k`` replicas' worth of capacity; returns how many
        the provider granted tickets for."""
        granted = 0
        for _ in range(k):
            try:
                ticket = self.provider.request()
            except CapacityUnavailable:
                with self._lock:
                    self.counts["denied"] += 1
                break
            with self._lock:
                self._pending.append(ticket)
            granted += 1
        return granted

    def _release(self, retired_idxs: List[int]) -> None:
        """Give retired replicas' capacity back to the provider
        (replicas the pool was BORN with carry no ticket and nothing
        is released for them)."""
        for idx in retired_idxs:
            with self._lock:
                ticket = self._ticket_by_idx.pop(idx, None)
            if ticket is not None:
                try:
                    self.provider.release(ticket)
                except Exception:
                    pass

    # ------------------------------------------------------ lifecycle

    def run(self, interval_s: float = 0.5) -> "PoolAutoscaler":
        """Start the control loop in a daemon thread."""
        if self._thread is None:
            self._stop.clear()

            def loop():
                while not self._stop.is_set():
                    try:
                        self.tick()
                    except Exception:
                        pass       # a broken tick must not kill the loop
                    self._stop.wait(interval_s)

            self._thread = threading.Thread(
                target=loop, name="pool-autoscaler", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the loop (the pool keeps its current size)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None

    def stats(self) -> Dict[str, Any]:
        """The ``autoscale`` block in ``pool_stats()`` / artifacts."""
        with self._lock:
            out = dict(self.counts)
            out["pending"] = len(self._pending)
            out["last_decision"] = self.last_decision
        out["target_replicas"] = self.target_replicas()
        out["min_replicas"] = self.policy.min_replicas
        out["max_replicas"] = self.policy.max_replicas
        return out
