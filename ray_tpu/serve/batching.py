"""@serve.batch: dynamic request batching.

Capability parity with the reference's batching (python/ray/serve/
batching.py:46,215 _BatchQueue): concurrent calls to the decorated async
method are queued and flushed to the underlying function as ONE list call
when max_batch_size is reached or batch_wait_timeout_s elapses. The
TPU payoff: a pjit replica sees full batches, keeping the MXU busy.
"""
from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self.queue: List = []
        self._flush_task: Optional[asyncio.Task] = None
        self._lock = asyncio.Lock()

    async def submit(self, instance, item):
        fut = asyncio.get_event_loop().create_future()
        async with self._lock:
            self.queue.append((item, fut))
            if len(self.queue) >= self.max_batch_size:
                await self._flush(instance)
            elif self._flush_task is None or self._flush_task.done():
                self._flush_task = asyncio.get_event_loop().create_task(
                    self._timed_flush(instance))
        return await fut

    async def _timed_flush(self, instance):
        await asyncio.sleep(self.timeout_s)
        async with self._lock:
            await self._flush(instance)

    async def _flush(self, instance):
        if not self.queue:
            return
        batch, self.queue = self.queue, []
        items = [b[0] for b in batch]
        futs = [b[1] for b in batch]
        try:
            if instance is not None:
                results = self.fn(instance, items)
            else:
                results = self.fn(items)
            if asyncio.iscoroutine(results):
                results = await results
            if len(results) != len(items):
                raise ValueError(
                    f"@batch function returned {len(results)} results "
                    f"for {len(items)} inputs")
            for fut, r in zip(futs, results):
                if not fut.done():
                    fut.set_result(r)
        except Exception as e:  # noqa: BLE001
            for fut in futs:
                if not fut.done():
                    fut.set_exception(e)


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator for (async) methods taking a single request; the wrapped
    implementation receives a list of requests and returns a list."""

    def wrap(fn):
        queue_attr = f"__batch_queue_{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:          # bound method: (self, item)
                instance, item = args
                q = getattr(instance, queue_attr, None)
                if q is None:
                    q = _BatchQueue(fn, max_batch_size,
                                    batch_wait_timeout_s)
                    setattr(instance, queue_attr, q)
                return await q.submit(instance, item)
            (item,) = args              # free function
            q = getattr(wrapper, "_queue", None)
            if q is None:
                q = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)
                wrapper._queue = q
            return await q.submit(None, item)

        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
