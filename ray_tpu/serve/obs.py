"""Serving observability: typed event log, trace export, flight recorder.

The serving stack's only internal record used to be ``sched_trace`` — an
untyped tuple deque on the engine. This module replaces it with a typed,
timestamped event substrate shared by the engine, the replica pool, the
watchdog, and the autoscaler, plus everything built on top of it:

- ``EventLog`` — a bounded ring of ``(seq, t, etype, rid, sid, data)``
  tuples with a LOCK-FREE append. The hot path (decode dispatch) pays
  one ``time.monotonic()`` call, one tuple allocation, and two
  GIL-atomic stores — the same cost class as the deque append it
  subsumes. Readers (``snapshot``/``tail``) tolerate concurrent
  appends: a torn read loses ring slots, never corrupts them.
- ``SchedTraceView`` — the compat facade: renders the four legacy
  scheduler-trace kinds (``prefill``/``decode``/``spec``/``cache_hit``)
  back to their EXACT historical tuple shapes so tests asserting on
  ``eng.sched_trace`` keep passing unchanged. New event kinds never
  leak through the view (callers unpack 2-tuples over the whole list).
- ``chrome_trace`` — Chrome/Perfetto trace-event JSON export merging
  any number of event streams (engine, pool, watchdog, autoscaler)
  onto one timeline, with derived per-request phase spans.
- ``request_phases`` — per-request lifecycle reconstruction (queue wait,
  prefill, decode, TTFT) from the raw event list; the basis for
  ``tools/trace_report.py`` and the tracing bridge.
- ``emit_request_spans`` — bridge into ``util/tracing.py``'s span model:
  each request becomes a root span with phase children, carrying the
  trace id minted at the HTTP proxy.
- ``dump_flight_bundle`` — the flight recorder: a postmortem bundle
  (event tails, ``load_report``, lifecycle/prefix/spec stats, allocator
  occupancy) written on ``ReplicaWedged``/``EngineFault``/chaos-end so
  a force-killed replica's last moments survive it. Every probe is
  best-effort: half-dead engines and test fakes must not break a dump.
- ``phase_metrics`` — lazy ``serve_phase_*`` Histogram singletons
  (queue_wait, plan, dispatch, readback, round wall, TTFT, inter-token)
  in ``util/metrics`` so the dashboard's ``/metrics`` endpoint exposes
  phase latency distributions.

``serve/scheduler.py`` stays device- and obs-free (its import whitelist
is test-enforced); the engine times the planner call from outside.
"""
from __future__ import annotations

import itertools
import json
import os
import time
import warnings
from typing import Any, Dict, Iterable, List, Optional

# Event tuple layout: (seq, t, etype, rid, sid, data)
#   seq   — per-log monotonically increasing index (total order)
#   t     — time.monotonic() at append
#   etype — event kind string ("admit", "decode", "route", ...)
#   rid   — request id, tuple of rids for batched events, or None
#   sid   — slot / replica index or None
#   data  — kind-specific payload (legacy-shape tuples for the four
#           sched_trace kinds; dicts elsewhere)
SEQ, T, ETYPE, RID, SID, DATA = range(6)

# The four kinds SchedTraceView renders back to legacy tuples.
LEGACY_KINDS = ("prefill", "decode", "spec", "cache_hit")


class EventLog:
    """Bounded ring of typed events with lock-free append.

    ``append`` never takes a lock: the ring slots are preallocated and
    the (index read, slot store, index store) sequence is GIL-atomic
    per operation — a concurrent reader may miss the newest entry or
    see an overwritten oldest one, never a torn record. ``enabled``
    False turns append into a single attribute test (the A/B arm).
    """

    __slots__ = ("name", "capacity", "enabled", "_ring", "_idx")

    def __init__(self, capacity: int = 4096, *, name: str = "engine",
                 enabled: bool = True):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.name = name
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._ring: List[Optional[tuple]] = [None] * self.capacity
        self._idx = 0

    def append(self, etype: str, rid: Any = None, sid: Any = None,
               data: Any = None, t: Optional[float] = None) -> None:
        if not self.enabled:
            return
        i = self._idx
        self._ring[i % self.capacity] = (
            i, time.monotonic() if t is None else t, etype, rid, sid,
            data)
        self._idx = i + 1

    @property
    def total(self) -> int:
        """Events ever appended (>= len once the ring has wrapped)."""
        return self._idx

    def __len__(self) -> int:
        idx = self._idx
        return self.capacity if idx > self.capacity else idx

    def snapshot(self) -> List[tuple]:
        """Ordered (oldest -> newest) copy of the retained events."""
        idx, cap = self._idx, self.capacity
        if idx <= cap:
            evs = [e for e in self._ring[:idx] if e is not None]
        else:
            cut = idx % cap
            evs = [e for e in self._ring[cut:] + self._ring[:cut]
                   if e is not None]
        # concurrent appends can reorder across the wrap point
        evs.sort(key=lambda e: e[SEQ])
        return evs

    def tail(self, n: int = 256) -> List[tuple]:
        return self.snapshot()[-int(n):]

    def clear(self) -> None:
        self._ring = [None] * self.capacity
        self._idx = 0


def as_dicts(events: Iterable[tuple]) -> List[Dict[str, Any]]:
    """Event tuples -> JSON-friendly dicts (artifact / bundle form)."""
    return [{"seq": e[SEQ], "t": e[T], "type": e[ETYPE],
             "rid": list(e[RID]) if isinstance(e[RID], tuple)
             else e[RID],
             "sid": e[SID], "data": _jsonable(e[DATA])}
            for e in events]


def event_window(events: List[tuple], total: int, cursor: int,
                 limit: int) -> tuple:
    """Cursored read over a bounded ring snapshot: the scrape seam.

    Returns ``(window, next_cursor, dropped)`` where ``window`` is
    the (<= limit) events with ``seq >= cursor``, ``next_cursor``
    resumes exactly after the last event handed out, and ``dropped``
    counts events the ring already overwrote past the cursor — the
    collector surfaces that as data loss instead of silently skipping.
    """
    cursor = max(0, int(cursor))
    limit = max(1, int(limit))
    oldest = events[0][SEQ] if events else total
    dropped = max(0, oldest - cursor)
    window = [e for e in events if e[SEQ] >= cursor][:limit]
    next_cursor = (window[-1][SEQ] + 1) if window \
        else max(cursor, total)
    return window, next_cursor, dropped


def _jsonable(x: Any) -> Any:
    if isinstance(x, tuple):
        return [_jsonable(v) for v in x]
    if isinstance(x, list):
        return [_jsonable(v) for v in x]
    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    return repr(x)


class SchedTraceView:
    """Legacy ``sched_trace`` facade over an :class:`EventLog`.

    Renders ONLY the four historical kinds, each with its exact legacy
    shape — callers unpack ``(kind, payload)`` 2-tuples over the whole
    list (and 4-tuples for ``spec``), so nothing else may leak through:

    - ``("prefill", ((ix, take), ...))``
    - ``("decode", steps)``
    - ``("spec", sid, proposed, accepted)``
    - ``("cache_hit", (slot, skipped_tokens))``
    """

    __slots__ = ("_log",)

    def __init__(self, log: EventLog):
        self._log = log

    def _tuples(self):
        for e in self._log.snapshot():
            etype = e[ETYPE]
            if etype == "prefill":
                yield ("prefill", e[DATA])
            elif etype == "decode":
                yield ("decode", e[DATA])
            elif etype == "spec":
                yield ("spec", e[SID], e[DATA][0], e[DATA][1])
            elif etype == "cache_hit":
                yield ("cache_hit", (e[SID], e[DATA]))

    def __iter__(self):
        return self._tuples()

    def __len__(self) -> int:
        return sum(1 for _ in self._tuples())

    def __contains__(self, item) -> bool:
        return any(t == item for t in self._tuples())

    def __bool__(self) -> bool:
        return any(True for _ in self._tuples())

    def append(self, item: tuple) -> None:
        """Compat escape hatch: accept a legacy tuple and record it as
        the corresponding typed event (external writers only — the
        engine appends typed events directly)."""
        kind = item[0]
        if kind == "spec":
            self._log.append("spec", sid=item[1],
                             data=(item[2], item[3]))
        elif kind == "cache_hit":
            self._log.append("cache_hit", sid=item[1][0],
                             data=item[1][1])
        elif kind in ("prefill", "decode"):
            self._log.append(kind, data=item[1])
        else:
            raise ValueError(f"unknown sched_trace kind {kind!r}")


# --------------------------------------------------------------- phases

# Point-event kinds that mark request-lifecycle boundaries.
_TERMINAL = ("retire", "cancelled", "deadline_exceeded",
             "fault_failed", "retry_exhausted", "shed", "failed")


def request_phases(events: Iterable[tuple]) -> Dict[Any, Dict[str, Any]]:
    """Reconstruct per-request phase timings from an event list.

    Returns ``{rid: phases}`` where phases carries the raw marks
    (``submit``/``admit``/``first_token``/``end`` monotonic stamps),
    the derived durations (``queue_wait_s``, ``prefill_s``,
    ``decode_s``, ``ttft_s``, ``total_s`` — None when a mark is
    missing), the terminal outcome, emit/decode-round counts, and the
    request's ``trace_id`` when a submit event carried one.
    """
    out: Dict[Any, Dict[str, Any]] = {}

    def rec(rid):
        return out.setdefault(rid, {
            "submit": None, "admit": None, "first_token": None,
            "end": None, "outcome": None, "trace_id": None,
            "n_emits": 0, "n_tokens": 0, "sid": None,
        })

    for e in events:
        etype, rid = e[ETYPE], e[RID]
        if rid is None or isinstance(rid, tuple):
            continue
        r = rec(rid)
        t = e[T]
        if etype == "submit":
            r["submit"] = t
            if isinstance(e[DATA], dict):
                r["trace_id"] = e[DATA].get("trace_id")
        elif etype == "admit":
            # resubmit-after-preemption re-admits: keep the first
            if r["admit"] is None:
                r["admit"] = t
            r["sid"] = e[SID]
        elif etype == "first_token":
            r["first_token"] = t
        elif etype == "emit":
            r["n_emits"] += 1
            if isinstance(e[DATA], dict):
                r["n_tokens"] += int(e[DATA].get("n", 0))
            r["end"] = t if r["end"] is None else max(r["end"], t)
        elif etype in _TERMINAL:
            r["outcome"] = etype
            r["end"] = t if r["end"] is None else max(r["end"], t)
    for r in out.values():
        sub, adm = r["submit"], r["admit"]
        ft, end = r["first_token"], r["end"]
        r["queue_wait_s"] = (adm - sub) if sub is not None \
            and adm is not None else None
        r["prefill_s"] = (ft - adm) if adm is not None \
            and ft is not None else None
        r["decode_s"] = (end - ft) if ft is not None \
            and end is not None else None
        r["ttft_s"] = (ft - sub) if sub is not None \
            and ft is not None else None
        r["total_s"] = (end - sub) if sub is not None \
            and end is not None else None
    return out


# --------------------------------------------------------- chrome trace

def chrome_trace(streams: Dict[str, Iterable[tuple]],
                 t0: Optional[float] = None) -> List[Dict[str, Any]]:
    """Merge event streams into Chrome trace-event JSON (Perfetto).

    ``streams`` maps a stream name ("engine-0", "pool", "watchdog") to
    its event tuples. Each stream becomes one process row (instant
    events, tid = sid); per-request phase spans derived from the merged
    stream land on a synthetic "requests" process with one thread row
    per request. Timestamps are microseconds relative to the earliest
    event, so the result is self-contained and monotone.
    """
    named = [(name, list(evs)) for name, evs in sorted(streams.items())]
    all_evs = [e for _n, evs in named for e in evs]
    if t0 is None:
        t0 = min((e[T] for e in all_evs), default=0.0)
    trace: List[Dict[str, Any]] = []
    pid = 0
    for name, evs in named:
        pid += 1
        trace.append({"name": "process_name", "ph": "M", "pid": pid,
                      "tid": 0, "args": {"name": name}})
        for e in evs:
            sid = e[SID]
            trace.append({
                "name": e[ETYPE], "ph": "i", "s": "t",
                "ts": round((e[T] - t0) * 1e6, 3),
                "pid": pid, "tid": sid if isinstance(sid, int) else 0,
                "args": {"rid": _jsonable(e[RID]), "seq": e[SEQ],
                         "data": _jsonable(e[DATA])},
            })
    # Derived per-request phase spans on their own process row.
    req_pid = pid + 1
    trace.append({"name": "process_name", "ph": "M", "pid": req_pid,
                  "tid": 0, "args": {"name": "requests"}})
    for rid, ph in sorted(request_phases(all_evs).items(),
                          key=lambda kv: str(kv[0])):
        tid = rid if isinstance(rid, int) else 0
        trace.append({"name": "thread_name", "ph": "M", "pid": req_pid,
                      "tid": tid, "args": {"name": f"req {rid}"}})

        def _span(name, a, b):
            if a is None or b is None or b < a:
                return
            trace.append({
                "name": name, "ph": "X",
                "ts": round((a - t0) * 1e6, 3),
                "dur": round((b - a) * 1e6, 3),
                "pid": req_pid, "tid": tid,
                "args": {"rid": _jsonable(rid),
                         "trace_id": ph.get("trace_id")},
            })
        _span("request", ph["submit"], ph["end"])
        _span("queue_wait", ph["submit"], ph["admit"])
        _span("prefill", ph["admit"], ph["first_token"])
        _span("decode", ph["first_token"], ph["end"])
    return trace


# -------------------------------------------------------- tracing bridge

def emit_request_spans(events: Iterable[tuple]) -> List[Dict[str, Any]]:
    """Bridge engine events into ``util/tracing``'s span model.

    Each reconstructed request becomes a root ``serve.request`` span
    (trace id = the one minted at the HTTP proxy when present) with
    ``queue_wait``/``prefill``/``decode`` children. Spans are returned
    always and additionally emitted through the tracing pipeline when
    tracing is enabled, so they merge with RPC spans in
    ``get_spans()``.
    """
    from ray_tpu.util import tracing
    # map the event log's monotonic stamps onto the wall clock tracing
    # uses; one offset sampled here keeps relative phase math exact
    off = time.time() - time.monotonic()
    spans: List[Dict[str, Any]] = []
    for rid, ph in sorted(request_phases(events).items(),
                          key=lambda kv: str(kv[0])):
        if ph["submit"] is None or ph["end"] is None:
            continue
        trace_id = ph.get("trace_id") or tracing._new_id()
        root_id = tracing._new_id()

        def mk(name, a, b, parent, span_id=None):
            return {
                "name": name, "kind": "serve.phase",
                "trace_id": trace_id,
                "span_id": span_id or tracing._new_id(),
                "parent_id": parent,
                "start_time": off + a, "end_time": off + b,
                "status": "ok" if ph["outcome"] in (None, "retire")
                else "error",
                "attributes": {"rid": _jsonable(rid),
                               "outcome": ph["outcome"]},
            }
        spans.append(mk("serve.request", ph["submit"], ph["end"],
                        None, span_id=root_id))
        if ph["admit"] is not None:
            spans.append(mk("serve.queue_wait", ph["submit"],
                            ph["admit"], root_id))
        if ph["admit"] is not None and ph["first_token"] is not None:
            spans.append(mk("serve.prefill", ph["admit"],
                            ph["first_token"], root_id))
        if ph["first_token"] is not None:
            spans.append(mk("serve.decode", ph["first_token"],
                            ph["end"], root_id))
    if tracing.is_enabled():
        for s in spans:
            tracing._emit(s)
    return spans


# ------------------------------------------------------- flight recorder

_FLIGHT_DIR_ENV = "RAY_TPU_FLIGHT_DIR"
_bundle_seq = itertools.count()


def default_flight_dir() -> str:
    return os.environ.get(_FLIGHT_DIR_ENV) or os.path.join(
        "/tmp", "ray_tpu", "flight", f"p{os.getpid()}")


def _slug(s: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "-"
                   for c in str(s))[:48] or "bundle"


def _probe(out: Dict[str, Any], key: str, fn) -> None:
    try:
        out[key] = fn()
    except Exception as e:  # noqa: BLE001 — postmortems never raise
        out[key + "_error"] = repr(e)


_LIFECYCLE_KEYS = ("submitted", "admitted", "completed", "shed",
                   "cancelled", "deadline_exceeded",
                   "contained_faults", "retries", "retry_exhausted",
                   "fault_failed", "preemptions", "force_killed")


def _probe_engine(eng, tail: int) -> Dict[str, Any]:
    """LOCK-FREE engine probe. The dump typically runs while a wedged
    scheduler thread holds the engine lock (that is the point of a
    flight recorder), so nothing here may wait on it: attribute reads
    are GIL-atomic, ``load_report()`` bounds its lock acquire and
    falls back to lock-free reads, and the lifecycle/spec sections
    are derived from a stats snapshot instead of calling the locked
    ``lifecycle_stats``/``spec_stats`` accessors."""
    out: Dict[str, Any] = {}
    log = getattr(eng, "events", None)
    if isinstance(log, EventLog):
        evs = log.tail(tail)
        out["events"] = as_dicts(evs)
        out["events_total"] = log.total
        if evs:
            out["last_event_t"] = evs[-1][T]
            out["event_gap_s"] = round(
                max(0.0, time.monotonic() - evs[-1][T]), 6)
    if callable(getattr(eng, "load_report", None)):
        _probe(out, "load_report", lambda: dict(eng.load_report()))
    rpt = out.get("load_report") or {}
    hb = rpt.get("heartbeat_age_s")
    gaps = [g for g in (hb, out.get("event_gap_s")) if g is not None]
    if gaps:
        # the postmortem headline: how long the scheduler was silent
        out["heartbeat_gap_s"] = round(max(gaps), 6)
    stats = getattr(eng, "stats", None)
    if stats is not None:
        _probe(out, "stats", lambda: dict(stats))
        s = out.get("stats") or {}
        out["lifecycle"] = {k: s.get(k, 0) for k in _LIFECYCLE_KEYS}
        spec = {k: v for k, v in s.items()
                if isinstance(k, str) and k.startswith("spec_")}
        if spec:
            out["spec"] = spec
    pc = getattr(eng, "prefix_cache", None)
    if pc is not None and callable(getattr(pc, "stats", None)):
        _probe(out, "prefix", pc.stats)
    kvm = getattr(eng, "kv_migration_stats", None)
    if kvm:
        # cross-replica KV pull counters: a migration fault's
        # postmortem must show whether pages moved, aborted, or fell
        # back to recompute
        _probe(out, "kv_migration", lambda: dict(kvm))
    alloc = getattr(eng, "alloc", None)
    if alloc is not None:
        _probe(out, "allocator", lambda: {
            "n_pages": alloc.n_pages, "n_free": alloc.n_free,
            "occupancy": alloc.occupancy(),
            # dtype-aware bytes view (None on pre-bytes allocators)
            "page_bytes": getattr(alloc, "page_bytes", None),
            "bytes_in_use": alloc.bytes_in_use()
            if callable(getattr(alloc, "bytes_in_use", None)) else None,
            "bytes_total": alloc.bytes_total()
            if callable(getattr(alloc, "bytes_total", None)) else None})
    return out


def _probe_pool(pool, tail: int) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    log = getattr(pool, "events", None)
    if isinstance(log, EventLog):
        out["events"] = as_dicts(log.tail(tail))
    if callable(getattr(pool, "pool_stats", None)):
        _probe(out, "pool_stats", pool.pool_stats)
    return out


def dump_flight_bundle(dirpath: Optional[str], reason: str, *,
                       engine=None, pool=None, watchdog=None,
                       extra: Optional[Dict[str, Any]] = None,
                       tail: int = 512) -> Optional[str]:
    """Write a postmortem bundle; returns its directory (None on total
    IO failure — the recorder must never turn a postmortem into a new
    fault). Layout: ``<dir>/<reason>-<seq>-p<pid>/bundle.json`` plus
    ``events.jsonl`` (engine then pool event tails, one per line).
    """
    root = dirpath or default_flight_dir()
    bdir = os.path.join(root, "%s-%06d-p%d" % (
        _slug(reason), next(_bundle_seq), os.getpid()))
    bundle: Dict[str, Any] = {
        "reason": str(reason),
        "t_wall": time.time(),
        "t_mono": time.monotonic(),
        "pid": os.getpid(),
    }
    if engine is not None:
        bundle["engine"] = _probe_engine(engine, tail)
    if pool is not None:
        bundle["pool"] = _probe_pool(pool, tail)
    if watchdog is not None:
        wd: Dict[str, Any] = {}
        if callable(getattr(watchdog, "stats", None)):
            _probe(wd, "stats", watchdog.stats)
        wlog = getattr(watchdog, "log", None)
        if isinstance(wlog, list):
            wd["log"] = [dict(e) for e in wlog[-tail:]]
        bundle["watchdog"] = wd
    if extra:
        bundle["extra"] = _jsonable(extra)
    try:
        os.makedirs(bdir, exist_ok=True)
        with open(os.path.join(bdir, "bundle.json"), "w") as f:
            json.dump(bundle, f, indent=2, default=repr)
        with open(os.path.join(bdir, "events.jsonl"), "w") as f:
            for section in ("engine", "pool"):
                for ev in bundle.get(section, {}).get("events", []):
                    f.write(json.dumps(
                        dict(ev, stream=section), default=repr) + "\n")
    except OSError:
        return None
    return bdir


def load_flight_bundle(bdir: str) -> Dict[str, Any]:
    """Load a bundle for postmortem reading.

    ``events.jsonl`` is parsed with the WAL torn-tail discipline
    (serve/fleet/wal.py): the dumper may have died mid-append, so a
    final line that does not parse — or a tail with no terminating
    newline — marks a torn tail. It is truncated in place with a
    warning and everything before it is returned; a postmortem reader
    must never raise over the very crash it is documenting. A torn
    line ANYWHERE but the tail is real corruption and still raises.
    """
    with open(os.path.join(bdir, "bundle.json")) as f:
        bundle = json.load(f)
    epath = os.path.join(bdir, "events.jsonl")
    if os.path.exists(epath):
        events: List[Dict[str, Any]] = []
        torn = 0
        with open(epath, "r+") as f:
            good_end = 0
            raw = f.read()
            lines = raw.split("\n")
            # a non-empty final element means the last write lost its
            # newline mid-append — that fragment is torn by definition
            complete, fragment = lines[:-1], lines[-1]
            for i, line in enumerate(complete):
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    if i != len(complete) - 1 or fragment:
                        raise
                    torn += 1
                    break
                good_end += len(line.encode("utf-8")) + 1
            if fragment:
                torn += 1
            if torn:
                warnings.warn(
                    f"flight bundle {bdir}: events.jsonl has a torn "
                    f"final line ({torn} record(s) truncated, "
                    f"{len(events)} retained) — the dumper likely "
                    f"died mid-append", RuntimeWarning,
                    stacklevel=2)
                f.seek(good_end)
                f.truncate(good_end)
        bundle["events_jsonl"] = events
        bundle["events_torn_truncated"] = torn
    return bundle


# --------------------------------------------------------- phase metrics

QUEUE_WAIT = "serve_phase_queue_wait_s"
PLAN = "serve_phase_plan_s"
DISPATCH = "serve_phase_dispatch_s"
READBACK = "serve_phase_readback_s"
ROUND_WALL = "serve_phase_round_wall_s"
TTFT = "serve_phase_ttft_s"
INTER_TOKEN = "serve_phase_inter_token_s"
HOST_GAP = "serve_phase_host_gap_s"

_PHASE_BOUNDS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)

_METRICS: Optional[Dict[str, Any]] = None


def phase_metrics() -> Dict[str, Any]:
    """Lazy serve_phase_* Histogram singletons (same rebuild-on-
    clear_registry pattern as the engine/pool metric builders)."""
    global _METRICS
    from ray_tpu.util import metrics
    if _METRICS is None or metrics.registry().get(QUEUE_WAIT) is not \
            _METRICS["queue_wait"]:
        _METRICS = {
            "queue_wait": metrics.Histogram(
                QUEUE_WAIT, "Submit-to-admit wait per request",
                boundaries=_PHASE_BOUNDS),
            "plan": metrics.Histogram(
                PLAN, "Pure-planner time per scheduling round",
                boundaries=_PHASE_BOUNDS),
            "dispatch": metrics.Histogram(
                DISPATCH, "Device dispatch time per scheduling round",
                boundaries=_PHASE_BOUNDS),
            "readback": metrics.Histogram(
                READBACK, "Host readback (device_get) time per drain",
                boundaries=_PHASE_BOUNDS),
            "round_wall": metrics.Histogram(
                ROUND_WALL, "Wall time per scheduling round",
                boundaries=_PHASE_BOUNDS),
            "ttft": metrics.Histogram(
                TTFT, "Time to first token per request",
                boundaries=_PHASE_BOUNDS),
            "inter_token": metrics.Histogram(
                INTER_TOKEN, "Mean gap between emitted tokens "
                "(per readback batch)",
                boundaries=_PHASE_BOUNDS),
            "host_gap": metrics.Histogram(
                HOST_GAP, "Host time gating dispatch per round "
                "(pre-plan readback drain + planner): the device "
                "idles for this span under the lockstep loop, and "
                "for ~none of it under the overlapped loop",
                boundaries=_PHASE_BOUNDS),
        }
    return _METRICS


def mint_trace_id() -> str:
    """A fresh 16-hex trace id (same shape util/tracing mints)."""
    from ray_tpu.util import tracing
    return tracing._new_id()
