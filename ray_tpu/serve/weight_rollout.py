"""Live weight rollout: hot checkpoint swap under traffic.

The learner->actor weight-publish path (RLAX / Podracer style) applied
to serving: a new checkpoint is published in the air/checkpoint.py
sha256-manifest format, each replica streams it in off the hot path and
flips between scheduler rounds under the engine's monotonic
weight-generation fence (``LLMEngine.swap_weights``), and a staged
controller walks the fleet through it — canary a configurable fraction
of replicas, watch health + output-parity probes, advance on green,
auto-rollback on regression.

Identity model: the **generation** is a per-engine strictly monotonic
fence (every swap advances it, rollbacks included), so "which payload
is serving" is named by the **weights_id** — derived here from the
checkpoint payload's canonical content (tree paths + dtypes + raw
leaf bytes), so the same bytes always get the same id — across
independent publishes, not just within one directory — and a rollback
provably converges the fleet back onto the old payload. Every transition is evented into the pool ring and the
terminal transitions (rollback, completion) are flight-bundle-
explained.

Failure stances:

- torn / corrupt checkpoint: ``load_weights`` deep-verifies against
  the manifest and refuses typed (``InvalidCheckpointError``) before
  any replica is touched.
- replica killed mid-swap: the swap raises; the pool's death path
  rebuilds the replica (and ``EnginePool._restamp_weights`` re-stamps
  it from the recorded weight source), the controller re-attempts a
  bounded number of times, then rolls the fleet back rather than
  leaving it torn.
- controller killed mid-rollout: per-replica ``weights_id`` is the
  durable state. A fresh ``rollout()`` call skips replicas already on
  the target payload, so re-running the controller resumes (or
  ``rollback`` converges everyone back).
"""
from __future__ import annotations

import hashlib
import math
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu.air.checkpoint import (Checkpoint, InvalidCheckpointError,
                                    verify_checkpoint_dir)
from ray_tpu.serve import obs
from ray_tpu.serve.engine import _metrics as _engine_metrics

HEALTHY_STATES = ("healthy", "suspect")


def weights_id_from_manifest(manifest: Dict[str, Any]) -> str:
    """Legacy payload identity: a digest over the manifest's per-file
    sha256 table. Stable for one committed directory, but NOT across
    republishes of the same tensors — the array store embeds
    per-write metadata, so byte-identical payloads serialize to
    different files. Kept for auditing a specific directory;
    ``publish_weights``/``load_weights`` stamp ids with
    ``weights_id_from_payload`` instead."""
    h = hashlib.sha256()
    for rel in sorted(manifest.get("files") or {}):
        rec = manifest["files"][rel]
        h.update(rel.encode())
        h.update(str(rec.get("sha256")).encode())
    return h.hexdigest()[:12]


def weights_id_from_payload(data: Dict[str, Any]) -> str:
    """Canonical payload identity: a digest over the checkpoint
    dict's tree paths, dtypes, shapes and raw leaf bytes (metadata
    entries included, so release tags still distinguish byte-identical
    tensors). Same content -> same id across independent publishes —
    the property the RLHF resume proof (republish the recovered
    params, land on the recovered id) and rollback convergence rely
    on."""
    import numpy as np
    h = hashlib.sha256()

    def walk(prefix: str, v: Any) -> None:
        if isinstance(v, dict):
            for k in sorted(v):
                walk(f"{prefix}/{k}", v[k])
            return
        if isinstance(v, (list, tuple)):
            for i, x in enumerate(v):
                walk(f"{prefix}/{i}", x)
            return
        h.update(prefix.encode())
        try:
            a = np.asarray(v)
        except Exception:
            a = None
        if a is None or a.dtype == object:
            h.update(repr(v).encode())
        else:
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(np.ascontiguousarray(a).tobytes())

    walk("", data)
    return h.hexdigest()[:12]


def publish_weights(params, path: str, step: Optional[int] = None,
                    extra: Optional[Dict[str, Any]] = None
                    ) -> Tuple[str, str]:
    """Publish ``params`` as a committed checkpoint directory (stage
    -> fsync -> manifest -> atomic rename; never observable torn).
    ``extra`` entries (release tags, training metadata) ride in the
    payload and distinguish the ``weights_id`` even when the tensors
    are byte-identical. Returns ``(path, weights_id)``."""
    data = dict(extra or {})
    data["params"] = params
    out = Checkpoint.from_dict(data).to_directory(path, step=step)
    ok, reason, _manifest = verify_checkpoint_dir(out)
    if not ok:                                    # pragma: no cover
        raise InvalidCheckpointError(out, reason)
    return out, weights_id_from_payload(data)


def load_weights(path: str) -> Tuple[Any, str]:
    """Deep-verify then load a published checkpoint's params. A torn,
    truncated, or bit-rotted directory is refused TYPED
    (``InvalidCheckpointError``) before any replica is touched.
    Returns ``(params, weights_id)``."""
    ok, reason, _manifest = verify_checkpoint_dir(path, deep=True)
    if not ok:
        raise InvalidCheckpointError(path, reason)
    data = Checkpoint.from_directory(path).to_dict()
    if "params" not in data:
        raise InvalidCheckpointError(
            path, "checkpoint carries no 'params' entry")
    return data["params"], weights_id_from_payload(data)


def publish_and_swap(engine, params, path: str, *,
                     step: Optional[int] = None, mode: str = "preempt",
                     extra: Optional[Dict[str, Any]] = None
                     ) -> Tuple[int, str]:
    """In-process publish -> swap shortcut for a co-located learner
    (the RLHF loop's per-update path): commit ``params`` as a durable
    manifest checkpoint, then install them on ``engine`` under the next
    generation. The durable copy is what a restarted generator re-syncs
    from; the swap is what live decode picks up. Returns
    ``(generation, weights_id)``."""
    _, wid = publish_weights(params, path, step=step, extra=extra)
    gen = engine.swap_weights(
        params, generation=engine.weight_generation + 1,
        weights_id=wid, mode=mode)
    return gen, wid


class WeightRolloutController:
    """Staged fleet rollout over an ``EnginePool``.

    ``canary_fraction`` of live replicas swap first; ``probes`` —
    ``(prompt_ids, expected_ids)`` pairs — run against each canary
    (greedy output parity: the new payload must reproduce its golden
    outputs), TTFT EWMAs are compared against the pre-rollout baseline
    through the load_report plane, and only a green canary lets the
    remaining waves advance. Any regression rolls every touched
    replica back to the baseline payload under a FRESH generation (the
    fence never retreats) and flight-explains the decision."""

    def __init__(self, pool, *, canary_fraction: float = 0.34,
                 probes: Optional[Sequence[Tuple[Sequence[int],
                                                 Sequence[int]]]] = None,
                 ttft_ratio_limit: Optional[float] = 3.0,
                 ttft_floor_s: float = 0.05,
                 swap_mode: str = "preempt",
                 max_swap_attempts: int = 3,
                 rebuild_wait_s: float = 10.0,
                 flight_dir: Optional[str] = None):
        if not 0.0 < canary_fraction <= 1.0:
            raise ValueError("canary_fraction must be in (0, 1]")
        self.pool = pool
        self.canary_fraction = float(canary_fraction)
        self.probes = [(list(p), list(e)) for p, e in (probes or ())]
        self.ttft_ratio_limit = ttft_ratio_limit
        self.ttft_floor_s = float(ttft_floor_s)
        self.swap_mode = swap_mode
        self.max_swap_attempts = max(1, int(max_swap_attempts))
        self.rebuild_wait_s = float(rebuild_wait_s)
        self.flight_dir = flight_dir

    # ----------------------------------------------------------- state

    def _live_replicas(self) -> List[Dict[str, Any]]:
        return [r for r in self.pool.pool_stats()["replicas"]
                if r["state"] in HEALTHY_STATES]

    def fleet_weights(self) -> Dict[int, Tuple[int, Optional[str]]]:
        """Per-replica ``idx -> (weight_generation, weights_id)`` for
        live replicas — the durable rollout state a resuming
        controller reads."""
        return {r["idx"]: (r["weight_generation"], r["weights_id"])
                for r in self._live_replicas()}

    # ---------------------------------------------------------- health

    def _probe_replica(self, idx: int) -> List[Dict[str, Any]]:
        """Run every parity probe directly against replica ``idx``
        (bypassing routing on purpose: the probe adjudicates THIS
        replica's payload). Returns the failures."""
        eng = self.pool.replica(idx).engine
        failures: List[Dict[str, Any]] = []
        for pi, (prompt, expected) in enumerate(self.probes):
            try:
                out = eng.submit(list(prompt),
                                 max_new_tokens=len(expected)).result()
            except Exception as e:  # noqa: BLE001
                failures.append({"probe": pi, "error": repr(e)})
                continue
            if list(out) != list(expected):
                failures.append({"probe": pi, "got": list(out),
                                 "want": list(expected)})
        return failures

    def _health_regression(self, idx: int,
                           baseline_ttft: Optional[float]
                           ) -> Optional[str]:
        """Post-swap health through the telemetry plane: the replica
        must be alive and its TTFT EWMA must not have blown past the
        baseline ratio. Returns a reason string on regression."""
        try:
            rpt = self.pool.replica(idx).engine.load_report()
        except Exception as e:  # noqa: BLE001
            return f"load_report failed: {e!r}"
        if rpt.get("stopped"):
            return "replica stopped after swap"
        if self.ttft_ratio_limit is not None:
            cur = rpt.get("ttft_ewma_s")
            if cur is not None and baseline_ttft is not None:
                floor = max(baseline_ttft, self.ttft_floor_s)
                if cur > self.ttft_ratio_limit * floor:
                    return (f"ttft regression: {cur:.4f}s > "
                            f"{self.ttft_ratio_limit:.1f}x baseline "
                            f"{baseline_ttft:.4f}s")
        return None

    # ------------------------------------------------------------ swap

    def _swap_one(self, idx: int, params, weights_id: str,
                  transitions: List[Dict[str, Any]]) -> bool:
        """Swap one replica with bounded retry across a mid-swap
        death: the pool's death path rebuilds the replica (re-stamped
        from the recorded weight source), and the next attempt lands
        on the fresh incarnation."""
        for attempt in range(self.max_swap_attempts):
            rep = self.pool.replica(idx)
            before = getattr(rep.engine, "weight_generation", 0)
            try:
                gen = self.pool.swap_replica_weights(
                    idx, params, weights_id=weights_id,
                    mode=self.swap_mode)
                transitions.append({"idx": idx, "from": before,
                                    "to": gen,
                                    "weights_id": weights_id,
                                    "attempt": attempt})
                return True
            except Exception as e:  # noqa: BLE001
                self.pool.events.append(
                    "weight_swap_failed", sid=idx,
                    data={"attempt": attempt, "error": repr(e)})
                if not self._await_live(idx):
                    return False
        return False

    def _await_live(self, idx: int) -> bool:
        """Wait (bounded) for replica ``idx`` to be live again — the
        auto-restart rebuild after a mid-swap kill."""
        deadline = time.monotonic() + self.rebuild_wait_s
        while time.monotonic() < deadline:
            try:
                if self.pool.replica(idx).state in HEALTHY_STATES:
                    return True
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.05)
        try:
            return self.pool.replica(idx).state in HEALTHY_STATES
        except Exception:  # noqa: BLE001
            return False

    # --------------------------------------------------------- rollout

    def rollout(self, new_params, *, weights_id: str,
                baseline_params, baseline_weights_id: str
                ) -> Dict[str, Any]:
        """Stage the fleet onto ``new_params``. Returns a report dict
        with ``status`` of ``"completed"`` or ``"rolled_back"`` (the
        rollback reason rides along), per-replica generation
        transitions, and the canary/probe evidence. Replicas already
        serving ``weights_id`` are skipped, which is also the resume
        path after a controller death."""
        live = self._live_replicas()
        if not live:
            raise RuntimeError("no live replicas to roll out to")
        pending = [r["idx"] for r in live
                   if r["weights_id"] != weights_id]
        done_already = [r["idx"] for r in live
                        if r["weights_id"] == weights_id]
        baseline_ttft = {}
        for r in live:
            try:
                baseline_ttft[r["idx"]] = self.pool.replica(
                    r["idx"]).engine.load_report().get("ttft_ewma_s")
            except Exception:  # noqa: BLE001
                baseline_ttft[r["idx"]] = None
        n_canary = max(1, math.ceil(
            self.canary_fraction * (len(pending) + len(done_already))))
        # resume path: replicas already converged count against the
        # canary quota — a re-run after a controller death re-canaries
        # only what the dead controller never proved
        canary = pending[:max(0, n_canary - len(done_already))]
        waves: List[List[int]] = []
        rest = pending[len(canary):]
        wave_size = max(1, n_canary)
        for i in range(0, len(rest), wave_size):
            waves.append(rest[i:i + wave_size])
        transitions: List[Dict[str, Any]] = []
        report: Dict[str, Any] = {
            "weights_id": weights_id,
            "baseline_weights_id": baseline_weights_id,
            "canary": list(canary),
            "waves": [list(w) for w in waves],
            "resumed": list(done_already),
            "transitions": transitions,
            "probe_failures": [],
        }
        self.pool.events.append("rollout_start", data={
            "weights_id": weights_id, "canary": list(canary),
            "pending": list(pending), "resumed": list(done_already)})

        def _rollback(reason: str) -> Dict[str, Any]:
            rb = self.rollback(baseline_params,
                               baseline_weights_id=baseline_weights_id,
                               reason=reason,
                               transitions=transitions)
            report.update(status="rolled_back",
                          rollback=rb, rollback_reason=reason)
            return report

        # -------------------------------------------------- canary wave
        for idx in canary:
            self.pool.events.append("canary", sid=idx,
                                    data={"weights_id": weights_id})
            if not self._swap_one(idx, new_params, weights_id,
                                  transitions):
                return _rollback(
                    f"canary replica {idx} could not swap "
                    f"(died mid-swap and did not recover)")
        for idx in canary:
            failures = self._probe_replica(idx)
            if failures:
                report["probe_failures"] = failures
                return _rollback(
                    f"canary replica {idx} failed "
                    f"{len(failures)}/{len(self.probes)} parity "
                    f"probes")
            regression = self._health_regression(
                idx, baseline_ttft.get(idx))
            if regression:
                return _rollback(
                    f"canary replica {idx} health: {regression}")
        # ------------------------------------------------ advance waves
        for wave in waves:
            self.pool.events.append("advance", data={
                "replicas": list(wave), "weights_id": weights_id})
            for idx in wave:
                if not self._swap_one(idx, new_params, weights_id,
                                      transitions):
                    return _rollback(
                        f"replica {idx} could not swap during "
                        f"advance")
                regression = self._health_regression(
                    idx, baseline_ttft.get(idx))
                if regression:
                    return _rollback(
                        f"replica {idx} health after advance: "
                        f"{regression}")
        # ------------------------------------------------- convergence
        stragglers = [i for i, (_g, wid)
                      in self.fleet_weights().items()
                      if wid != weights_id]
        if stragglers:
            return _rollback(
                f"fleet did not converge: replicas {stragglers} not "
                f"on {weights_id}")
        fleet_gen = max(g for g, _ in self.fleet_weights().values())
        self.pool.set_weight_source(new_params, weights_id=weights_id,
                                    generation=fleet_gen)
        self.pool.events.append("rollout_done", data={
            "weights_id": weights_id, "generation": fleet_gen})
        obs.dump_flight_bundle(
            self.flight_dir, "weight-rollout-done", pool=self.pool,
            extra={"weights_id": weights_id,
                   "generation": fleet_gen,
                   "transitions": transitions})
        report.update(status="completed", generation=fleet_gen)
        return report

    # -------------------------------------------------------- rollback

    def rollback(self, baseline_params, *, baseline_weights_id: str,
                 reason: str,
                 transitions: Optional[List[Dict[str, Any]]] = None
                 ) -> Dict[str, Any]:
        """Converge every live replica back onto the baseline payload.
        The fence never retreats: each touched replica swaps to the
        OLD params under a NEW generation; ``weights_id`` equality is
        the convergence proof. Evented, counted, and
        flight-explained."""
        transitions = transitions if transitions is not None else []
        self.pool.events.append("rollback", data={
            "weights_id": baseline_weights_id, "reason": reason})
        failed: List[int] = []
        for idx, (_gen, wid) in sorted(self.fleet_weights().items()):
            if wid == baseline_weights_id:
                continue
            if not self._swap_one(idx, baseline_params,
                                  baseline_weights_id, transitions):
                failed.append(idx)
        converged = not failed and all(
            wid == baseline_weights_id
            for _g, wid in self.fleet_weights().values())
        if converged:
            fleet_gen = max(
                g for g, _ in self.fleet_weights().values())
            self.pool.set_weight_source(
                baseline_params, weights_id=baseline_weights_id,
                generation=fleet_gen)
        with self.pool._lock:
            self.pool.route_stats["weight_rollbacks"] += 1
        _engine_metrics()["weight_rollbacks"].inc()
        bundle = obs.dump_flight_bundle(
            self.flight_dir, "weight-rollback", pool=self.pool,
            extra={"reason": reason,
                   "baseline_weights_id": baseline_weights_id,
                   "converged": converged,
                   "failed_replicas": failed,
                   "fleet": {str(i): {"generation": g,
                                      "weights_id": w}
                             for i, (g, w)
                             in self.fleet_weights().items()}})
        return {"reason": reason, "converged": converged,
                "failed_replicas": failed, "bundle": bundle,
                "fleet": self.fleet_weights()}
