from ray_tpu.serve.api import (deployment, run, shutdown, get_deployment,
                               get_handle, list_deployments)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig

__all__ = ["deployment", "run", "shutdown", "get_deployment", "get_handle",
           "list_deployments", "batch", "AutoscalingConfig",
           "DeploymentConfig"]
