from ray_tpu.serve.api import (delete, deployment, run, shutdown,
                               get_deployment, get_handle,
                               get_deployment_handle,
                               list_deployments, status)
from ray_tpu.serve.errors import (DeadlineExceeded, EngineDraining,
                                  EngineOverloaded, EngineShutdown,
                                  RequestCancelled, RequestError)
from ray_tpu.serve.multiplex import (get_multiplexed_model_id,
                                     multiplexed)
from ray_tpu.serve.drivers import (DAGDriver, json_request,
                                   json_to_ndarray)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.ingress import ingress, route
from ray_tpu.serve.router import StreamingResponse

__all__ = ["deployment", "run", "shutdown", "get_deployment", "get_handle",
           "list_deployments", "status", "delete", "DAGDriver",
           "json_request", "json_to_ndarray", "batch",
           "multiplexed", "get_multiplexed_model_id",
           "get_deployment_handle", "ingress", "route",
           "AutoscalingConfig", "DeploymentConfig", "StreamingResponse",
           "RequestError", "RequestCancelled", "DeadlineExceeded",
           "EngineOverloaded", "EngineShutdown", "EngineDraining"]
