"""HTTP ingress proxy.

Capability parity with the reference's HTTPProxy
(serve/_private/http_proxy.py:189 — uvicorn/starlette there, aiohttp here):
routes POST/GET /<deployment_name> to the deployment handle; JSON body
becomes the request argument; response is JSON. One proxy per node in the
distributed runtime; serve.start_http() runs it in a background thread.
"""
from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.api import get_handle, list_deployments
from ray_tpu.serve.errors import classify_http_status, retry_after_s


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        # Optional fleet TelemetryCollector (serve/fleet/telemetry.py):
        # when attached, /-/metrics serves the CLUSTER exposition —
        # every member's families re-labeled member=<name> plus
        # collector health — instead of just this process's registry.
        self.telemetry_collector = None
        self._handles: Dict[str, Any] = {}
        self._runner = None
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        # Dedicated pool for blocking handle calls: streaming long-polls
        # park a thread per in-flight chunk wait, which would starve the
        # loop's small default executor (and /healthz with it).
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(
            max_workers=128, thread_name_prefix="serve-proxy")

    @staticmethod
    def _error_response(e: BaseException):
        """Map request-lifecycle failures to their HTTP contract
        (serve/errors.py classify_http_status, matching BY NAME
        across the remote-call wrapping): EngineOverloaded -> 429 +
        Retry-After, DeadlineExceeded / ray_tpu.get timeout -> 504,
        EngineShutdown / EngineDraining -> 503, RequestCancelled ->
        499, everything else stays a 500. Always a clean JSON body —
        a timeout must not surface as a 500 with a traceback.

        Retry-After honesty: ``retry_after_s`` takes the MAX hint
        over the whole cause chain, so an engine-pool shed (one
        aggregate EngineOverloaded chaining per-replica sheds)
        advertises the slowest replica's hint; the ceiling below
        means the header never tells a client to return before the
        hint says capacity could be back."""
        from aiohttp import web
        status = classify_http_status(e)
        body = {"error": str(e) or type(e).__name__,
                "type": type(e).__name__}
        if status == 504:
            body["error"] = (str(e)
                             or "upstream timed out before replying")
        headers = {}
        if status == 429:
            headers["Retry-After"] = str(
                max(1, -(-int(retry_after_s(e) * 1000) // 1000)))
        elif status == 503:
            # Degraded/draining pools attach a restart/provisioning
            # ETA when they have one (PoolDegraded.retry_after_s,
            # EngineShutdown with an autoscaler hint) — surface it
            # instead of a bare 503. No hint along the chain
            # (default=0.0) means no header: an invented Retry-After
            # is worse than none.
            hint = retry_after_s(e, default=0.0)
            if hint > 0:
                headers["Retry-After"] = str(
                    max(1, -(-int(hint * 1000) // 1000)))
        return web.json_response(body, status=status,
                                 headers=headers)

    def _handle_for(self, name: str):
        h = self._handles.get(name)
        if h is None:
            if name not in list_deployments():
                return None
            h = get_handle(name)
            self._handles[name] = h
        return h

    @staticmethod
    def _mint_trace_id(request, payload):
        """Request-scope trace id, OPT-IN only: honor an
        ``X-Trace-Id`` header, or mint one when span tracing is
        enabled process-wide. Returns the id (after injecting it
        into a dict payload that lacks one) or None — the default
        path never touches the payload, preserving the exact-echo
        body contract."""
        tid = request.headers.get("X-Trace-Id")
        if tid is None:
            from ray_tpu.util import tracing
            if not tracing.is_enabled():
                return None
            from ray_tpu.serve import obs
            tid = obs.mint_trace_id()
        if isinstance(payload, dict):
            payload.setdefault("trace_id", tid)
        return tid

    async def _read_payload(self, request):
        """(payload, error_response): JSON body for body-carrying
        verbs, query dict otherwise."""
        from aiohttp import web
        if request.method in ("POST", "PUT", "PATCH") and \
                request.can_read_body:
            try:
                return await request.json(), None
            except json.JSONDecodeError:
                return None, web.json_response(
                    {"error": "body must be JSON"}, status=400)
        return dict(request.query) or None, None

    async def _dispatch(self, request):
        from aiohttp import web
        name = request.match_info["deployment"]
        handle = self._handle_for(name)
        if handle is None:
            return web.json_response(
                {"error": f"no deployment {name!r}"}, status=404)
        payload, err = await self._read_payload(request)
        if err is not None:
            return err
        # Streaming is transport metadata: opt in via the query string
        # ONLY (?stream=1). POST bodies are never inspected or
        # modified — a deployment may legitimately take a "stream"
        # key. (Exception, equally opt-in: an X-Trace-Id header or
        # process-wide tracing injects a "trace_id" key so the id
        # can ride through pool routing into the engine event log.)
        stream = request.query.get("stream") in ("1", "true")
        if stream and request.method != "POST":
            payload.pop("stream", None)     # strip it from query args
            payload = payload or None
        tid = self._mint_trace_id(request, payload)
        # X-Replica, OPT-IN like X-Trace-Id: a request header (any
        # value) asks which replica incarnation served the call; the
        # flag rides the dict payload to the deployment, which
        # answers {"ids": ..., "replica": "<id>:<gen>"} — popped
        # back out here into the response header so the JSON body
        # stays identical to the non-opted response. Dict payloads
        # only (same rule as trace_id injection: the proxy never
        # invents a payload shape). Streams opt in too: the
        # deployment yields {"replica": ...} as its FIRST item,
        # which the proxy lifts into the header before committing
        # chunked encoding — the tag names the incarnation that
        # ACCEPTED the stream (a mid-flight resubmit can move it;
        # unary tags have the same admission-time meaning).
        echo_rep = (isinstance(payload, dict)
                    and "X-Replica" in request.headers)
        if echo_rep:
            payload.setdefault("echo_replica", True)
        # X-Model-Generation: same opt-in contract, but the tag names
        # the WEIGHTS that served the call ("<generation>:<weights_id>")
        # — the half of replica identity that a live rollout changes
        # without restarting the process.
        echo_gen = (isinstance(payload, dict)
                    and "X-Model-Generation" in request.headers)
        if echo_gen:
            payload.setdefault("echo_generation", True)
        try:
            if stream:
                return await self._dispatch_stream(request, handle,
                                                   payload,
                                                   trace_id=tid)
            ref = handle.remote(payload) if payload is not None \
                else handle.remote()
            loop = asyncio.get_event_loop()
            result = await loop.run_in_executor(
                self._pool, lambda: ray_tpu.get(ref, timeout=60))
            headers = {}
            if tid:
                headers["X-Trace-Id"] = tid
            if isinstance(result, dict) and \
                    ((echo_rep and "replica" in result) or
                     (echo_gen and "generation" in result)):
                if echo_rep and "replica" in result:
                    headers["X-Replica"] = str(result.pop("replica"))
                if echo_gen and "generation" in result:
                    headers["X-Model-Generation"] = \
                        str(result.pop("generation"))
                result = result.get("ids", result)
            return web.json_response({"result": result},
                                     headers=headers or None)
        except asyncio.CancelledError:
            # client disconnected mid-request (aiohttp cancels the
            # handler): there is nobody to answer — the 499-style
            # outcome is the closed connection itself
            raise
        except Exception as e:  # noqa: BLE001
            return self._error_response(e)

    async def _dispatch_stream(self, request, handle, payload,
                               trace_id=None):
        """Chunked-transfer streaming: each chunk from the deployment's
        generator is one newline-delimited JSON line (reference:
        serve/_private/http_util.py streaming responses)."""
        from aiohttp import web
        loop = asyncio.get_event_loop()
        method = handle.options(stream=True)
        sr = await loop.run_in_executor(
            self._pool, lambda: method.remote(payload)
            if payload is not None else method.remote())
        it = iter(sr)

        def _next():
            try:
                return True, next(it)
            except StopIteration:
                return False, None
        # Pull the FIRST chunk before committing chunked encoding:
        # request-lifecycle failures that fire before any token
        # (shed at submit -> 429, deadline while queued -> 504) then
        # map to real status codes instead of a 200 with an error
        # line buried in the stream.
        try:
            more, first = await loop.run_in_executor(self._pool,
                                                     _next)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            return self._error_response(e)
        headers = {"Content-Type": "application/x-ndjson"}
        if trace_id:
            headers["X-Trace-Id"] = trace_id
        # Opted-in streams lead with a marker item carrying "replica"
        # and/or "generation" keys (llm.py stream()): lift them into
        # headers while we still CAN set headers, then pull the real
        # first token.
        if more and isinstance(first, dict) and \
                ("replica" in first or "generation" in first):
            if "replica" in first:
                headers["X-Replica"] = str(first["replica"])
            if "generation" in first:
                headers["X-Model-Generation"] = \
                    str(first["generation"])
            try:
                more, first = await loop.run_in_executor(self._pool,
                                                         _next)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                return self._error_response(e)
        resp = web.StreamResponse(headers=headers)
        resp.enable_chunked_encoding()
        await resp.prepare(request)
        # Once prepare() has committed chunked encoding we can never
        # return a second (json) response: mid-stream failures become a
        # terminal {"error": ...} line on the stream itself.
        try:
            while more:
                await resp.write(
                    (json.dumps({"chunk": first}, default=str) +
                     "\n").encode())
                more, first = await loop.run_in_executor(self._pool,
                                                         _next)
        except Exception as e:  # noqa: BLE001
            try:
                await resp.write(
                    (json.dumps({"error": str(e)}) + "\n").encode())
            except (ConnectionError, OSError):
                pass           # client already gone
        try:
            await resp.write_eof()
        except (ConnectionError, OSError):
            pass               # disconnect mid-stream: close quietly
        return resp

    async def _dispatch_route(self, request):
        """Subpath requests go to @serve.ingress deployments: the
        replica-side handle_route dispatcher matches the path template
        and verb (reference: FastAPI ingress routing,
        serve/http_adapters.py)."""
        from aiohttp import web
        name = request.match_info["deployment"]
        handle = self._handle_for(name)
        if handle is None:
            return web.json_response(
                {"error": f"no deployment {name!r}"}, status=404)
        subpath = "/" + request.match_info["tail"]
        payload, err = await self._read_payload(request)
        if err is not None:
            return err
        try:
            ref = handle.handle_route.remote(request.method, subpath,
                                             payload)
            loop = asyncio.get_event_loop()
            result = await loop.run_in_executor(
                self._pool, lambda: ray_tpu.get(ref, timeout=60))
            return web.json_response({"result": result})
        except Exception as e:  # noqa: BLE001
            msg = str(e)
            if "no attribute 'handle_route'" in msg:
                # Subpath on a deployment that isn't @serve.ingress.
                return web.json_response(
                    {"error": f"deployment {name!r} has no HTTP "
                              f"routes (not @serve.ingress)"},
                    status=404)
            # handle_route raises LookupError("404: ...")/("405: ...");
            # remote wrapping may prefix the message, so take the
            # FIRST status marker in the string.
            import re
            m = re.search(r"\b(40[45]): ", msg)
            if m:
                return web.json_response({"error": msg},
                                         status=int(m.group(1)))
            return self._error_response(e)

    async def _health(self, request):
        from aiohttp import web
        return web.json_response({"status": "ok",
                                  "deployments": list_deployments()})

    def attach_telemetry(self, collector) -> "HTTPProxy":
        """Point /-/metrics at a fleet ``TelemetryCollector`` so one
        curl returns the whole cluster's exposition (per-member
        labels + scrape/clock health) instead of only this
        process's registry."""
        self.telemetry_collector = collector
        return self

    async def _metrics(self, request):
        """Prometheus exposition. With a fleet collector attached
        this is the AGGREGATED view (member-labeled families from
        every scraped process + collector health gauges); otherwise
        it falls back to the local registry so the endpoint is
        always live."""
        from aiohttp import web
        col = self.telemetry_collector
        loop = asyncio.get_event_loop()
        if col is not None:
            # metrics_text() takes the collector lock and walks every
            # member's scraped text: off the event loop.
            text = await loop.run_in_executor(self._pool,
                                              col.metrics_text)
        else:
            from ray_tpu.util import metrics
            text = await loop.run_in_executor(self._pool,
                                              metrics.prometheus_text)
        return web.Response(
            text=text,
            content_type="text/plain",
            charset="utf-8")

    def _run(self):
        from aiohttp import web
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        app = web.Application()
        app.router.add_get("/-/healthz", self._health)
        app.router.add_get("/-/metrics", self._metrics)
        app.router.add_route("*", "/{deployment}", self._dispatch)
        app.router.add_route("*", "/{deployment}/{tail:.+}",
                             self._dispatch_route)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self.host, self.port)
        loop.run_until_complete(site.start())
        # port=0 binds an ephemeral port; report the real one
        if runner.addresses:
            self.port = runner.addresses[0][1]
        self._runner = runner
        self._started.set()
        loop.run_forever()

    def start(self, timeout: float = 10.0):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-http-proxy")
        self._thread.start()
        if not self._started.wait(timeout):
            raise TimeoutError("HTTP proxy failed to start")
        return self

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        self._pool.shutdown(wait=False)


_proxy: Optional[HTTPProxy] = None


def start_http(host: str = "127.0.0.1", port: int = 8000) -> HTTPProxy:
    global _proxy
    if _proxy is None:
        _proxy = HTTPProxy(host, port).start()
    return _proxy


def stop_http():
    global _proxy
    if _proxy is not None:
        _proxy.stop()
        _proxy = None
