"""Fleet-wide KV page migration: pull hot prefixes, don't recompute.

The radix prefix cache (serve/prefix_cache.py) is per-replica: a hot
system prompt is re-prefilled once per replica, and affinity routing
alone thrashes on multi-session traces (pool smoke: 0.14 hit rate,
519 evictions on a single shared prefix). The reference runtime's
plasma object manager solves exactly this shape with peer-to-peer
Push/Pull of immutable objects between nodes; our immutable objects
are already-computed KV pages, and the int8 pool format (PR 15)
halves their wire cost for free.

This module is the transfer protocol both deployment shapes share —
the in-process ``EnginePool`` (loopback wire toll) and the
process-separated fleet (``ReplicaAgent`` RPCs over sockets):

- **Donor side** (``KVDonor``): resolves a requester's prefix hashes
  to physical pages via ``PrefixCache.match_hashes`` — which PINS
  them (refcount increment) for the transfer lifetime, so eviction
  can never yank a page mid-pull — then serves bounded chunks of raw
  page bytes (int8 payload + per-page scales travel together,
  models/kv_cache.py ``export_page_bytes``). Transfers expire on a
  pin deadline: a requester that dies mid-pull cannot pin donor
  pages forever.
- **Requester side** (``pull_prefix``): chunked pull with per-pull
  deadline, bounded per-chunk retries with backoff, and dedupe keyed
  ``(digest, chunk_idx)`` so a duplicated or retried chunk can never
  double-land. A typed ``KVPullAborted`` (donor says the prefix is
  gone) aborts immediately; transport errors retry bounded, then
  abort. An aborted pull returns ``None`` — the engine falls back to
  plain prefill, it never wedges.

Chunks are sized to fit under the fleet transport's explicit
max-frame knob (``transport.max_frame_bytes``) with headroom for
base64 + envelope overhead, so a bulk KV chunk can never be the
frame that a telemetry scrape or control RPC bounces off.

Wire format (JSON-safe; no token ids ever cross — only rolling path
hashes, the same privacy property the affinity digests have):

    begin  -> {xfer_id, digest, n_pages, n_chunks, pages_per_chunk,
               page_size, kv_dtype, n_layers}
    chunk  -> {chunk_idx, pages: [[b64, ...] per layer] per page}
    end    -> {released: bool}
"""
from __future__ import annotations

import base64
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ray_tpu.serve.fleet import transport as fleet_transport
from ray_tpu.serve.fleet.transport import TransportError
from ray_tpu.serve.fleet.wire import KVPullAborted

PULLS = "serve_kv_migration_pulls_total"
PULLED_PAGES = "serve_kv_migration_pulled_pages_total"
WIRE_BYTES = "serve_kv_migration_wire_bytes_total"
ABORTS = "serve_kv_migration_aborts_total"
FALLBACKS = "serve_kv_migration_fallbacks_total"

_METRICS: Optional[dict] = None


def _metrics() -> dict:
    """Lazy module-level metric singletons, re-created if a test's
    ``clear_registry()`` dropped them (same pattern as the engine and
    prefix-cache modules)."""
    global _METRICS
    from ray_tpu.util import metrics
    if (_METRICS is None
            or metrics.registry().get(PULLS) is not _METRICS["pulls"]):
        _METRICS = {
            "pulls": metrics.Counter(
                PULLS, "Cross-replica KV prefix pulls attempted"),
            "pulled_pages": metrics.Counter(
                PULLED_PAGES, "KV pages landed from a peer replica "
                "instead of recomputed"),
            "wire_bytes": metrics.Counter(
                WIRE_BYTES, "Encoded KV payload bytes received over "
                "the fleet transport"),
            "aborts": metrics.Counter(
                ABORTS, "KV pulls aborted (typed donor refusal, "
                "donor death, or pull deadline)"),
            "fallbacks": metrics.Counter(
                FALLBACKS, "Requests that fell back to plain prefill "
                "after an incomplete pull"),
        }
    return _METRICS


def new_stats() -> Dict[str, int]:
    """Plain-int per-entity mirror of the process counters (engines
    and routers keep one so bench artifacts and pool_stats read local
    numbers, same convention as ``PrefixCache``'s mirrors)."""
    return {"pulls": 0, "pulled_pages": 0, "wire_bytes": 0,
            "aborts": 0, "fallbacks": 0}


# --------------------------------------------------------------- donor


class KVDonor:
    """Transfer table + export surface over ONE engine, shared by the
    ``ReplicaAgent`` RPC handlers and the in-process pool adapter.

    The engine contract (serve/engine.py): ``kv_pin_prefix(hashes)``
    pins and returns the longest resident page run,
    ``kv_export_pages(pages)`` reads raw page bytes, and
    ``kv_release_pages(pages)`` unpins — all under the engine lock.
    """

    def __init__(self, engine, *, pin_ttl_s: float = 30.0,
                 max_chunk_bytes: Optional[int] = None,
                 chunk_delay_s: float = 0.0,
                 time_fn: Callable[[], float] = time.monotonic):
        self._engine = engine
        self._pin_ttl_s = float(pin_ttl_s)
        self._max_chunk_bytes = max_chunk_bytes
        # chaos seam: stretch each chunk export so a harness can kill
        # the donor process deterministically MID-pull
        self.chunk_delay_s = float(chunk_delay_s)
        self._time = time_fn
        self._lock = threading.Lock()
        self._seq = 0
        self._xfers: Dict[str, Dict[str, Any]] = {}

    def _chunk_budget_bytes(self) -> int:
        """Raw payload bytes one chunk may carry: half the frame knob,
        leaving headroom for base64 (4/3x) plus JSON envelope."""
        budget = fleet_transport.max_frame_bytes() // 2
        if self._max_chunk_bytes is not None:
            budget = min(budget, int(self._max_chunk_bytes))
        return max(1, budget)

    def _gc_locked(self) -> None:
        now = self._time()
        for xid in [x for x, t in self._xfers.items()
                    if t["deadline"] <= now]:
            self._release(self._xfers.pop(xid))

    def _release(self, xfer: Dict[str, Any]) -> None:
        if not xfer.get("released"):
            xfer["released"] = True
            self._engine.kv_release_pages(xfer["pages"])

    def begin(self, hashes: Sequence[int]) -> Dict[str, Any]:
        """Pin the longest resident run of ``hashes`` and plan the
        chunked transfer. Raises typed ``KVPullAborted`` when nothing
        is resident (the requester's directory view was stale)."""
        hashes = [int(h) for h in hashes]
        pages = self._engine.kv_pin_prefix(hashes)
        if not pages:
            raise KVPullAborted(
                "prefix not resident on donor (evicted since "
                "advertised)")
        page_bytes = max(1, int(getattr(self._engine, "page_bytes",
                                        None) or 1))
        per_chunk = max(1, self._chunk_budget_bytes() // page_bytes)
        n_chunks = -(-len(pages) // per_chunk)
        with self._lock:
            self._gc_locked()
            self._seq += 1
            xid = f"x{self._seq}"
            self._xfers[xid] = {
                "pages": pages, "digest": hashes[len(pages) - 1],
                "per_chunk": per_chunk, "n_chunks": n_chunks,
                "deadline": self._time() + self._pin_ttl_s,
                "released": False,
            }
        return {"xfer_id": xid, "digest": hashes[len(pages) - 1],
                "n_pages": len(pages), "n_chunks": n_chunks,
                "pages_per_chunk": per_chunk,
                "page_size": self._engine.Pg,
                "kv_dtype": getattr(self._engine, "kv_dtype", "fp"),
                "n_layers": self._engine.cfg.n_layers}

    def chunk(self, xfer_id: str, chunk_idx: int) -> Dict[str, Any]:
        """Export one chunk's pages as base64 blobs. Idempotent (pure
        read of pinned pages), so duplicated or retried chunk RPCs are
        harmless. Unknown/expired transfers raise typed
        ``KVPullAborted`` — the pin is gone, the pages may not be."""
        with self._lock:
            self._gc_locked()
            xfer = self._xfers.get(xfer_id)
            if xfer is None:
                raise KVPullAborted(
                    f"unknown or expired transfer {xfer_id!r}")
            if not 0 <= int(chunk_idx) < xfer["n_chunks"]:
                raise KVPullAborted(
                    f"chunk {chunk_idx} out of range for {xfer_id!r}")
            lo = int(chunk_idx) * xfer["per_chunk"]
            pages = xfer["pages"][lo:lo + xfer["per_chunk"]]
        if self.chunk_delay_s > 0:
            time.sleep(self.chunk_delay_s)
        blobs = self._engine.kv_export_pages(pages)
        return {"chunk_idx": int(chunk_idx),
                "pages": [[[base64.b64encode(b).decode("ascii")
                            for b in layer_cols]
                           for layer_cols in page_blobs]
                          for page_blobs in blobs]}

    def end(self, xfer_id: str) -> Dict[str, Any]:
        """Unpin a finished transfer (best-effort from the requester;
        the pin deadline GC is the backstop when this call is lost)."""
        with self._lock:
            xfer = self._xfers.pop(xfer_id, None)
            if xfer is None:
                return {"released": False}
            self._release(xfer)
        return {"released": True}

    def open_transfers(self) -> int:
        with self._lock:
            self._gc_locked()
            return len(self._xfers)

    def handle(self, method: str, args: Dict[str, Any]) -> Any:
        """RPC-shaped dispatch (the in-process pool adapter routes a
        loopback wire through this; the agent calls begin/chunk/end
        directly from its ``rpc_`` handlers)."""
        if method == "kv_pull_begin":
            return self.begin(args["hashes"])
        if method == "kv_pull_chunk":
            return self.chunk(args["xfer_id"], args["chunk_idx"])
        if method == "kv_pull_end":
            return self.end(args["xfer_id"])
        raise KVPullAborted(f"unknown kv method {method!r}")


# ----------------------------------------------------------- requester


def pull_prefix(call: Callable[[str, Dict[str, Any]], Any],
                hashes: Sequence[int], *,
                deadline_s: float = 5.0,
                max_attempts: int = 3,
                backoff_s: float = 0.02,
                stats: Optional[Dict[str, int]] = None,
                time_fn: Callable[[], float] = time.monotonic
                ) -> Optional[Dict[str, Any]]:
    """Pull the longest donor-resident run of ``hashes`` over any
    ``call(method, args)`` seam. Returns ``{"n_pages", "page_size",
    "kv_dtype", "n_layers", "digest", "pages": [per-page [bytes per
    layer-col]], "wire_bytes"}`` — or ``None`` when the pull aborted
    (typed donor refusal, transport retries exhausted, or deadline):
    the caller falls back to plain prefill.

    Received chunks are deduped by ``(digest, chunk_idx)``: a
    duplicated delivery or a retry after a dropped response can never
    land a chunk twice or double-count its wire bytes.
    """
    m = _metrics()
    m["pulls"].inc()
    if stats is not None:
        stats["pulls"] += 1
    t0 = time_fn()

    def _abort() -> None:
        m["aborts"].inc()
        if stats is not None:
            stats["aborts"] += 1

    try:
        begin = call("kv_pull_begin", {"hashes": [int(h) for h
                                                  in hashes]})
    except (KVPullAborted, TransportError):
        _abort()
        return None
    digest = int(begin["digest"])
    n_chunks = int(begin["n_chunks"])
    got: Dict[Any, List[List[bytes]]] = {}
    wire_bytes = 0
    for idx in range(n_chunks):
        key = (digest, idx)
        if key in got:
            continue                      # dedupe: already landed
        attempts = 0
        while key not in got:
            if time_fn() - t0 > deadline_s:
                _abort()
                return None
            try:
                rsp = call("kv_pull_chunk",
                           {"xfer_id": begin["xfer_id"],
                            "chunk_idx": idx})
            except KVPullAborted:
                _abort()                  # typed: donor said no
                return None
            except TransportError:
                attempts += 1
                if attempts >= max_attempts:
                    _abort()              # donor unreachable
                    return None
                time.sleep(backoff_s * (2 ** (attempts - 1)))
                continue
            rkey = (digest, int(rsp["chunk_idx"]))
            if rkey in got:
                continue                  # duplicate delivery
            wire_bytes += sum(len(col) for page in rsp["pages"]
                              for layer in page for col in layer)
            got[rkey] = [
                [[base64.b64decode(col) for col in layer]
                 for layer in page]
                for page in rsp["pages"]]
    try:
        call("kv_pull_end", {"xfer_id": begin["xfer_id"]})
    except (KVPullAborted, TransportError):
        pass                              # pin GC is the backstop
    pages: List[List[bytes]] = []
    for idx in range(n_chunks):
        pages.extend(got[(digest, idx)])
    m["pulled_pages"].inc(len(pages))
    m["wire_bytes"].inc(wire_bytes)
    if stats is not None:
        stats["pulled_pages"] += len(pages)
        stats["wire_bytes"] += wire_bytes
    return {"n_pages": len(pages), "digest": digest,
            "page_size": int(begin["page_size"]),
            "kv_dtype": begin["kv_dtype"],
            "n_layers": int(begin["n_layers"]),
            "pages": pages, "wire_bytes": wire_bytes}


def validate_pull_knobs(deadline_s: Optional[float] = None,
                        backoff_s: Optional[float] = None
                        ) -> Dict[str, float]:
    """Typed validation for the requester-side pull knobs a
    deployment plumbs through (``LlamaDeployment(kv_pull_deadline_s=,
    kv_pull_backoff_s=)``). ``None`` means "use the ``pull_prefix``
    default"; anything else must be a positive finite number — a junk
    value fails HERE, at construction, not minutes later inside the
    first cache-miss pull. Returns only the overridden knobs, ready
    to splat into ``pull_prefix``."""
    knobs: Dict[str, float] = {}
    for name, val in (("deadline_s", deadline_s),
                      ("backoff_s", backoff_s)):
        if val is None:
            continue
        try:
            f = float(val)
        except (TypeError, ValueError):
            raise ValueError(
                f"kv pull {name} must be a positive number, "
                f"got {val!r}") from None
        if not (f > 0.0) or f != f or f == float("inf"):
            raise ValueError(
                f"kv pull {name} must be a positive finite number, "
                f"got {val!r}")
        knobs[name] = f
    return knobs


def prefill_push_hint(prompt: Sequence[int], page_size: int,
                      **donor: Any) -> Optional[Dict[str, Any]]:
    """Finished-prefill push hint: the donor-side twin of the cold
    routing pull. When a prefill-role replica completes a prompt, the
    pool hands the stream to a decode replica carrying THIS hint —
    the full-page hash chain of exactly the prompt the donor just
    retired into its prefix cache, plus the donor's address
    (``replica_idx=`` in-process, ``addr=``/``replica_id=`` over the
    fleet wire). The decode replica's admission pull then resumes at
    full prompt length instead of recomputing it: a degenerate
    "all pages pulled" prefill. Returns ``None`` when the prompt has
    no full page — nothing worth shipping, plain prefill is cheaper
    than a one-page wire round-trip says the PR 16 smoke."""
    from ray_tpu.serve.prefix_cache import path_hashes
    if page_size <= 0 or len(prompt) < page_size:
        return None
    n_full = len(prompt) // page_size
    chain = path_hashes(list(prompt), page_size)[:n_full]
    if not chain:
        return None
    hint: Dict[str, Any] = {"hashes": chain}
    hint.update(donor)
    return hint


def count_fallback(stats: Optional[Dict[str, int]] = None) -> None:
    """One request fell back to plain prefill after its pull failed
    or its pulled pages could not land (allocator dry)."""
    _metrics()["fallbacks"].inc()
    if stats is not None:
        stats["fallbacks"] += 1


def loopback_call(donor: KVDonor
                  ) -> Callable[[str, Dict[str, Any]], Any]:
    """In-process call seam over a donor that still pays the wire
    toll: every request/response JSON round-trips and typed errors
    cross via the wire error shape, exactly as over a socket — the
    ``EnginePool``'s fleet-shared arm measures honest wire bytes."""
    lb = fleet_transport.LoopbackTransport(
        lambda method, args, trace_id: donor.handle(method, args))
    return lambda method, args: lb.call(method, args)
