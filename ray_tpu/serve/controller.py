"""Serve control plane: controller + replica actors.

Capability parity with the reference's controller reconcile loop
(python/ray/serve/controller.py:61,229 run_control_loop), DeploymentState
replica state machine (serve/_private/deployment_state.py:56,942), replica
wrapper (serve/_private/replica.py:250) and request-driven autoscaling
(serve/_private/autoscaling_policy.py:93). TPU-native: a replica may be an
SPMD mesh gang — its actor builds a device mesh at startup and serves
pjit-compiled inference.
"""
from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig

CONTROLLER_NAME = "serve::controller"


class Replica:
    """Actor wrapping one instance of a deployment."""

    def __init__(self, deployment_name: str, replica_id: str,
                 cls, init_args, init_kwargs, mesh_axes=None,
                 user_config=None):
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        self.mesh = None
        if mesh_axes is not None:
            from ray_tpu.mesh import create_mesh
            self.mesh = create_mesh(mesh_axes)
        if cls is None:
            self.instance = None
        else:
            self.instance = cls(*init_args, **init_kwargs)
            if self.mesh is not None and \
                    hasattr(self.instance, "setup_mesh"):
                self.instance.setup_mesh(self.mesh)
            self._user_config = None
            if user_config is not None:
                self.reconfigure(user_config)
        self._ongoing = 0
        self._total = 0
        # _ongoing is mutated from the event loop AND pool threads
        # (streaming _finish): the read-modify-write must be locked or
        # lost updates drift the count autoscaling/draining read.
        import threading
        self._count_lock = threading.Lock()
        self._streams: Dict[str, Dict[str, Any]] = {}

    def reconfigure(self, user_config) -> bool:
        """Apply a user_config update IN PLACE (reference: the replica
        reconfigure hook — rolling updates without restarts). The
        instance's own ``reconfigure(user_config)`` does the work; a
        deployment without one simply records the config (visible via
        stats) so updates are not an error."""
        self._user_config = user_config
        fn = getattr(self.instance, "reconfigure", None)
        if callable(fn):
            fn(user_config)
            return True
        return False

    def _adjust_ongoing(self, delta: int):
        with self._count_lock:
            self._ongoing += delta
            if delta > 0:
                self._total += 1

    def _target_fn(self, method_name: str):
        target = self.instance
        if method_name == "__call__":
            return target
        return getattr(target, method_name)

    async def handle_request_streaming(self, req_id: str,
                                       method_name: str, args, kwargs):
        """Start a streaming request (reference: serve replica
        streaming responses, serve/_private/replica.py + http_util
        chunked encoding). The user method may return a generator /
        async generator (each item is a chunk) or a plain value (one
        chunk). Chunks buffer here; the caller drains them with
        next_chunks long-polls."""
        import inspect

        from ray_tpu.serve.multiplex import (MUX_KWARG,
                                             _set_request_model_id)
        _set_request_model_id(kwargs.pop(MUX_KWARG, None))
        loop = asyncio.get_event_loop()
        fn = self._target_fn(method_name)   # raises BEFORE any state
        self._reap_abandoned_streams()
        st = {"chunks": [], "done": False, "error": None,
              "base": 0, "event": asyncio.Event(),
              "last_poll": time.time(), "abandoned": False}
        self._streams[req_id] = st
        self._adjust_ongoing(+1)

        def _notify():
            loop.call_soon_threadsafe(st["event"].set)

        def _finish(error=None):
            if error is not None:
                st["error"] = error
            st["done"] = True
            self._adjust_ongoing(-1)
            _notify()

        # For __call__ the target IS the instance; inspect its bound
        # __call__ (the instance itself is never a genfunction).
        probe = getattr(fn, "__call__", fn) if not inspect.isfunction(
            fn) and not inspect.ismethod(fn) else fn
        unwrapped = getattr(probe, "__func__", probe)
        if inspect.isasyncgenfunction(unwrapped):
            async def _drain_async():
                try:
                    async for chunk in fn(*args, **kwargs):
                        if st["abandoned"]:
                            break       # consumer gone: stop buffering
                        st["chunks"].append(chunk)
                        st["event"].set()
                except Exception as e:       # noqa: BLE001
                    st["error"] = e
                finally:
                    st["done"] = True
                    self._adjust_ongoing(-1)
                    st["event"].set()
            asyncio.ensure_future(_drain_async())
            return True

        def _drain_sync():
            # Runs in the thread executor: generators from sync user
            # code iterate here so slow token production never blocks
            # the replica's event loop.
            try:
                result = fn(*args, **kwargs)
                if inspect.iscoroutine(result):
                    # plain `async def` method: await it on the loop,
                    # stream its return value as the single chunk
                    result = asyncio.run_coroutine_threadsafe(
                        result, loop).result()
                if inspect.isasyncgen(result):
                    async def _adrain():
                        async for c in result:
                            if st["abandoned"]:
                                break
                            st["chunks"].append(c)
                            st["event"].set()
                    asyncio.run_coroutine_threadsafe(
                        _adrain(), loop).result()
                elif inspect.isgenerator(result) or (
                        hasattr(result, "__next__")):
                    # only true iterators stream element-wise; plain
                    # iterable VALUES (arrays, sets) are one chunk
                    for chunk in result:
                        if st["abandoned"]:
                            break       # consumer gone: stop buffering
                        st["chunks"].append(chunk)
                        _notify()
                else:
                    st["chunks"].append(result)
            except Exception as e:           # noqa: BLE001
                _finish(e)
                return
            _finish()

        # copy_context: request-scoped ContextVars (multiplexed model
        # id) must follow the sync drain into the executor thread.
        import contextvars
        loop.run_in_executor(None, contextvars.copy_context().run,
                             _drain_sync)
        return True

    _STREAM_ABANDON_S = 120.0     # no poll for this long => abandoned

    def _reap_abandoned_streams(self):
        """Drop stream records whose consumer stopped polling (client
        disconnect / driver crash): the producer loop sees `abandoned`
        and stops buffering, bounding replica memory."""
        now = time.time()
        for rid in list(self._streams):
            st = self._streams[rid]
            # done-but-undrained records leak just the same as live
            # ones whose consumer vanished: both go by poll age.
            if now - st["last_poll"] > self._STREAM_ABANDON_S:
                st["abandoned"] = True
                st["chunks"].clear()
                self._streams.pop(rid, None)

    async def next_chunks(self, req_id: str, start: int,
                          timeout: float = 10.0):
        """Long-poll for chunks past ``start``; returns
        {chunks, done, error}. The stream record is dropped once the
        consumer has seen everything."""
        st = self._streams.get(req_id)
        if st is None:
            raise KeyError(f"unknown stream {req_id!r}")
        st["last_poll"] = time.time()
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        # indices are absolute; the buffer holds [base:] (acked chunks
        # are trimmed — single consumer per stream)
        while len(st["chunks"]) <= max(0, start - st["base"]) and \
                not st["done"]:
            remaining = deadline - loop.time()
            if remaining <= 0:
                break
            try:
                await asyncio.wait_for(st["event"].wait(), remaining)
            except asyncio.TimeoutError:
                break
            st["event"].clear()
        local = max(0, start - st["base"])
        chunks = st["chunks"][local:]
        done = st["done"] and (local + len(chunks)) == \
            len(st["chunks"])
        err = st["error"] if done else None
        if done:
            self._streams.pop(req_id, None)
        elif chunks:
            # single consumer: trim acknowledged chunks so a long
            # stream buffers O(unconsumed), not O(everything produced)
            drop = local + len(chunks)
            del st["chunks"][:drop]
            st["base"] += drop
        return {"chunks": chunks, "done": done, "error": err}

    async def handle_request(self, method_name: str, args, kwargs):
        self._adjust_ongoing(+1)
        try:
            from ray_tpu.serve.multiplex import (MUX_KWARG,
                                                 _set_request_model_id)
            _set_request_model_id(kwargs.pop(MUX_KWARG, None))
            target = self.instance
            if method_name == "__call__":
                fn = target
            else:
                fn = getattr(target, method_name)
            unwrapped = getattr(fn, "__func__", fn)
            if asyncio.iscoroutinefunction(unwrapped) or \
                    asyncio.iscoroutinefunction(
                        getattr(fn, "__call__", None)):
                return await fn(*args, **kwargs)
            # Sync callables run in the thread executor so they don't
            # block the replica's event loop (reference: serve replica
            # runs sync user code off-loop). copy_context carries
            # request-scoped ContextVars (multiplexed model id) into
            # the executor thread.
            import contextvars
            import functools
            loop = asyncio.get_event_loop()
            ctx = contextvars.copy_context()
            result = await loop.run_in_executor(
                None, ctx.run, functools.partial(fn, *args, **kwargs))
            if asyncio.iscoroutine(result):
                result = await result
            return result
        finally:
            self._adjust_ongoing(-1)

    def stats(self):
        self._reap_abandoned_streams()
        out = {"replica_id": self.replica_id,
               "user_config": getattr(self, "_user_config", None),
               "ongoing": self._ongoing,
               "total": self._total}
        # Optional user metrics hook (reference: serve's
        # record_metrics / RequestRouter stats): a deployment class
        # may expose serve_stats() -> dict; merged under "user" so
        # autoscaler/status surfaces see domain metrics (e.g. the
        # LLM engine's slot occupancy and token counters).
        # CONTRACT: the hook must be fast and non-blocking — stats()
        # feeds 2s-timeout controller polls (drain/autoscale); a
        # hook that blocks degrades them (timeouts are treated
        # conservatively, never as idleness).
        fn = getattr(self.instance, "serve_stats", None)
        if callable(fn):
            try:
                out["user"] = fn()
            except Exception as e:   # visible, never fatal
                out["user"] = {"error": repr(e)}
        return out

    def load_report(self):
        """Compact load snapshot for the controller's replica table:
        the deployment's ``load_report()`` hook (the LLM engine/pool
        publishes free slots, queue depth, outstanding tokens), plus
        the generic in-flight count. None-able fields stay absent —
        a replica without the hook still reports ``ongoing``."""
        out = {"ongoing": self._ongoing}
        fn = getattr(self.instance, "load_report", None)
        if callable(fn):
            try:
                rpt = fn()
                if rpt:
                    out.update(rpt)
            except Exception:    # hook failure must not mark us dead
                pass
        return out

    def health_check(self):
        """Controller liveness probe. A deployment class may define
        its own ``check_health()`` (reference: user-defined health
        checks, serve deployment_state) — an exception there marks
        the replica unhealthy and the controller replaces it."""
        fn = getattr(self.instance, "check_health", None)
        if callable(fn):
            fn()           # raising = unhealthy
        return True


class Controller:
    """Singleton async actor reconciling deployments to target state."""

    def __init__(self):
        # name -> dict(cls, init_args, init_kwargs, config, version,
        #              replicas: {rid: handle}, target, last_scale_*)
        self._deployments: Dict[str, Dict[str, Any]] = {}
        self._next_replica = 0
        self._running = True
        asyncio.get_event_loop().create_task(self._control_loop())

    # --- API ---------------------------------------------------------------

    def deploy(self, name: str, cls, init_args, init_kwargs,
               config: DeploymentConfig) -> None:
        d = self._deployments.get(name)
        if d is not None and self._only_user_config_changed(
                d, cls, init_args, init_kwargs, config):
            # Light path (reference: user_config-only updates roll
            # reconfigure() through live replicas, no restarts). The
            # acks are AWAITED: a reconfigure() that raises, or a
            # wedged replica, must not be reported as a successful
            # deploy — on any failure fall through to the versioned
            # redeploy, which replaces replicas wholesale.
            refs = []
            try:
                for h in list(d["replicas"].values()):
                    refs.append(
                        h.reconfigure.remote(config.user_config))
                # Bounded: a wedged replica must not stall the
                # controller mailbox longer than this.
                ray_tpu.get(refs, timeout=10)
            except Exception:
                pass          # heavy path below restarts replicas
            else:
                d["config"] = config
                return
        version = (d["version"] + 1) if d else 0
        target = config.num_replicas
        if config.autoscaling_config:
            target = max(config.autoscaling_config.min_replicas,
                         min(target,
                             config.autoscaling_config.max_replicas))
        self._deployments[name] = {
            "cls": cls, "init_args": init_args,
            "init_kwargs": init_kwargs, "config": config,
            "version": version,
            "replicas": dict(d["replicas"]) if d else {},
            "target": target,
            "last_upscale": 0.0, "last_downscale": 0.0,
            "old_version_replicas": set(d["replicas"]) if d else set(),
            # rid -> (handle, drain_start_ts); removed from routing but
            # kept alive until in-flight requests finish (reference:
            # STOPPING state in serve/_private/deployment_state.py:56).
            "draining": dict(d["draining"]) if d else {},
        }

    @staticmethod
    def _only_user_config_changed(d, cls, init_args, init_kwargs,
                                  config: DeploymentConfig) -> bool:
        import dataclasses
        old: DeploymentConfig = d["config"]
        a = dataclasses.replace(old, user_config=None)
        b = dataclasses.replace(config, user_config=None)
        if a != b or old.user_config == config.user_config:
            return False
        if old.user_config is None or config.user_config is None:
            # Setting or CLEARING user_config restarts: live replicas
            # would otherwise see reconfigure(None) while future
            # spawns (guarded on `is not None`) never get the call.
            return False
        # Code identity: the redeploy must carry the same class/args
        # (bit-identical pickles) or replicas need real restarts.
        import cloudpickle
        try:
            return (cloudpickle.dumps((cls, init_args, init_kwargs)) ==
                    cloudpickle.dumps((d["cls"], d["init_args"],
                                       d["init_kwargs"])))
        except Exception:
            return False

    def delete_deployment(self, name: str):
        d = self._deployments.pop(name, None)
        if d:
            for h in d["replicas"].values():
                self._kill(h)
            for h, _ in d["draining"].values():
                self._kill(h)

    def get_replicas(self, name: str):
        d = self._deployments.get(name)
        if d is None:
            raise ValueError(f"No deployment named {name!r}")
        cfg = d["config"]
        return {"version": d["version"],
                "replicas": list(d["replicas"].items()),
                "max_ongoing": cfg.max_ongoing_requests,
                # per-replica load snapshots (engine/pool
                # load_report), refreshed by the control loop; rides
                # the polling path only — pub/sub pushes stay scale-
                # event-driven so load churn can't flood the hub
                "loads": dict(d.get("loads") or {})}

    def _publish_replicas(self, name: str, d: Dict[str, Any]):
        """Push the replica table to the head's pub/sub hub so handles
        learn about scale events without polling (LongPollHost parity,
        serve/_private/long_poll.py:179). No-op on the local runtime
        (no head hub) — handles fall back to TTL refresh there."""
        fp = (d["version"], tuple(sorted(d["replicas"])),
              d["config"].max_ongoing_requests)
        if d.get("_published_fp") == fp:
            return
        from ray_tpu._private.worker import global_worker
        head = getattr(global_worker().runtime, "head", None)
        if head is None:
            return
        try:
            import cloudpickle
            # Pre-pickled: actor handles must deserialize in SUBSCRIBER
            # processes (which have runtimes), never in the head.
            head.call("publish", f"serve:replicas:{name}",
                      cloudpickle.dumps({
                          "version": d["version"],
                          "replicas": list(d["replicas"].items()),
                          "max_ongoing":
                              d["config"].max_ongoing_requests,
                      }))
            d["_published_fp"] = fp
        except Exception:
            pass   # hub unreachable: handles still have TTL fallback

    def list_deployments(self) -> Dict[str, Dict[str, Any]]:
        return {name: {"num_replicas": len(d["replicas"]),
                       "target": d["target"],
                       "version": d["version"]}
                for name, d in self._deployments.items()}

    def ready(self, name: str) -> bool:
        d = self._deployments.get(name)
        return (d is not None and
                len(d["replicas"]) >= max(1, d["target"]) and
                not d["old_version_replicas"])

    def shutdown(self):
        self._running = False
        for name in list(self._deployments):
            self.delete_deployment(name)

    # --- reconcile ---------------------------------------------------------

    @staticmethod
    def _kill(handle):
        try:
            ray_tpu.kill(handle)
        except Exception:
            pass

    def _spawn_replica(self, name: str, d: Dict[str, Any]):
        rid = f"{name}#{self._next_replica}"
        self._next_replica += 1
        cfg: DeploymentConfig = d["config"]
        opts = dict(cfg.ray_actor_options or {})
        opts.setdefault("num_cpus", 0.1)
        actor_cls = ray_tpu.remote(Replica)
        handle = actor_cls.options(
            max_concurrency=max(8, cfg.max_ongoing_requests),
            **opts).remote(
            name, rid, d["cls"], d["init_args"], d["init_kwargs"],
            cfg.mesh, cfg.user_config)
        d["replicas"][rid] = handle

    async def _control_loop(self):
        while self._running:
            try:
                for name, d in list(self._deployments.items()):
                    # Roll old-version replicas (drain, don't hard-kill).
                    for rid in list(d["old_version_replicas"]):
                        h = d["replicas"].pop(rid, None)
                        if h is not None:
                            d["draining"][rid] = (h, time.time())
                        d["old_version_replicas"].discard(rid)
                    # Scale to target.
                    while len(d["replicas"]) < d["target"]:
                        self._spawn_replica(name, d)
                    while len(d["replicas"]) > d["target"]:
                        rid, h = next(iter(d["replicas"].items()))
                        del d["replicas"][rid]
                        d["draining"][rid] = (h, time.time())
                    self._publish_replicas(name, d)
                    await self._drain(d)
                    await self._autoscale(name, d)
                    self._poll_loads(d)
                    self._health_check(name, d)
            except Exception:  # noqa: BLE001 — keep reconciling
                import traceback
                traceback.print_exc()
            await asyncio.sleep(0.05)

    # Load-table refresh cadence: snapshots are routing HINTS — a
    # tie-break, not an admission gate — so a second of staleness
    # costs one suboptimal route, and polling faster would just tax
    # replicas with stats traffic.
    _LOAD_POLL_S = 1.0

    def _poll_loads(self, d: Dict[str, Any]) -> None:
        """Refresh the per-replica load-snapshot table (the
        ``Replica.load_report`` passthrough of the engine/pool
        ``load_report()``). Handles read it via ``get_replicas`` and
        use queue depth / outstanding tokens as the P2C tie-break."""
        now = time.time()
        if now - d.get("_loads_polled_at", 0.0) < self._LOAD_POLL_S:
            return
        d["_loads_polled_at"] = now
        reps = list(d["replicas"].items())
        if not reps:
            d["loads"] = {}
            return
        refs = [h.load_report.remote() for _, h in reps]
        try:
            reports = ray_tpu.get(refs, timeout=2)
        except Exception:
            return     # keep the previous table: stale beats absent
        d["loads"] = {rid: rpt for (rid, _), rpt
                      in zip(reps, reports) if rpt}

    # Probe-failure policy: definitive death replaces immediately;
    # other errors and timeouts need this many CONSECUTIVE strikes
    # (transient transport blips must not execute an expensive
    # replica, e.g. a mesh gang with minutes of compile behind it).
    _HEALTH_STRIKES = 3

    def _health_check(self, name: str, d: Dict[str, Any]) -> None:
        """Periodic replica health probing (reference: serve's
        deployment-state health checks): every health_check_period_s
        each replica's health_check() is pinged without blocking the
        reconcile loop. A dead actor replaces the replica at once; a
        user check_health() exception, other probe errors, or probe
        timeouts replace it after _HEALTH_STRIKES consecutive
        failures (killed, not drained — it is presumed broken)."""
        cfg: DeploymentConfig = d["config"]
        period = getattr(cfg, "health_check_period_s", 5.0)
        if period <= 0:
            return
        now = time.time()
        pending = d.setdefault("_health_pending", {})
        strikes = d.setdefault("_health_strikes", {})
        # Replicas can leave d["replicas"] outside this function
        # (scale-down, redeploy) with no probe pending; sweep their
        # strike entries or the dict grows forever (rids are never
        # reused).
        for rid in list(strikes):
            if rid not in d["replicas"]:
                strikes.pop(rid, None)

        def strike(rid, h, definitive=False):
            n = strikes.get(rid, 0) + 1
            if definitive or n >= self._HEALTH_STRIKES:
                strikes.pop(rid, None)
                d["replicas"].pop(rid, None)
                self._kill(h)
                self._publish_replicas(name, d)
                # the scale-to-target pass spawns the replacement
            else:
                strikes[rid] = n

        # Resolve previously fired probes (non-blocking).
        for rid, (ref, fut, deadline) in list(pending.items()):
            h = d["replicas"].get(rid)
            if h is None:
                pending.pop(rid, None)
                strikes.pop(rid, None)
                continue
            if fut.done():
                pending.pop(rid, None)
                try:
                    fut.result()
                    strikes.pop(rid, None)      # healthy: reset
                except Exception as e:
                    from ray_tpu.exceptions import ActorDiedError
                    strike(rid, h,
                           definitive=isinstance(e, ActorDiedError))
            elif now > deadline:
                # A replica saturated with long requests must not be
                # executed for being busy — timeouts accumulate
                # strikes and only a consecutive run replaces it.
                pending.pop(rid, None)
                strike(rid, h)
        if now - d.get("_health_last", 0.0) < period:
            return
        d["_health_last"] = now
        for rid, h in list(d["replicas"].items()):
            if rid in pending:
                continue
            try:
                ref = h.health_check.remote()
                # The REF must stay alive alongside its future: eager
                # GC frees the reply object the moment the last ref
                # drops, which would fail every probe with
                # ObjectLostError.
                pending[rid] = (ref, ref.future(),
                                now + max(3 * period, 30.0))
            except Exception as e:
                # Submit-time death is definitive in the distributed
                # runtime (the route resolver raises ActorDiedError
                # for known-dead actors): a swallowed one here would
                # retry forever while the dead replica keeps counting
                # toward target.
                from ray_tpu.exceptions import ActorDiedError
                if isinstance(e, ActorDiedError):
                    strike(rid, h, definitive=True)
                # other submission failures: next round retries

    async def _drain(self, d: Dict[str, Any]):
        """Kill draining replicas once idle (or past their deadline).

        A minimum grace period of two router cache TTLs must elapse
        before an idle kill, so handles holding a stale replica list
        can't route onto a just-killed actor.
        """
        from ray_tpu.serve.router import _REFRESH_S
        for rid, (h, started) in list(d["draining"].items()):
            if time.time() - started < 2 * _REFRESH_S:
                continue
            try:
                stats = ray_tpu.get(h.stats.remote(), timeout=2)
                idle = stats["ongoing"] == 0
            except Exception:
                # Unreachable/slow stats (e.g. a user serve_stats()
                # hook blocking) is NOT evidence of idleness — keep
                # waiting; the 30s hard deadline below still bounds
                # the drain.
                idle = False
            if idle or time.time() - started > 30.0:
                del d["draining"][rid]
                self._kill(h)

    async def _autoscale(self, name: str, d: Dict[str, Any]):
        cfg: DeploymentConfig = d["config"]
        auto: Optional[AutoscalingConfig] = cfg.autoscaling_config
        if auto is None or not d["replicas"]:
            return
        refs = [h.stats.remote() for h in d["replicas"].values()]
        try:
            stats = ray_tpu.get(refs, timeout=2)
        except Exception:
            return
        ongoing = sum(s["ongoing"] for s in stats)
        avg = ongoing / max(1, len(stats))
        now = time.time()
        if avg > auto.target_ongoing_requests and \
                d["target"] < auto.max_replicas and \
                now - d["last_upscale"] > auto.upscale_delay_s:
            d["target"] += 1
            d["last_upscale"] = now
        elif avg < auto.target_ongoing_requests / 2 and \
                d["target"] > auto.min_replicas and \
                now - d["last_downscale"] > auto.downscale_delay_s:
            d["target"] -= 1
            d["last_downscale"] = now


def get_or_create_controller():
    cls = ray_tpu.remote(Controller)
    return cls.options(name=CONTROLLER_NAME, get_if_exists=True,
                       num_cpus=0).remote()
