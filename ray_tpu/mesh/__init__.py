from ray_tpu.mesh.device_mesh import (MeshSpec, best_mesh_shape,
                                      create_mesh, local_device_count)
from ray_tpu.mesh.sharding import (ShardingRules, batch_sharding,
                                   infer_sharding, replicated,
                                   shard_params, with_sharding)

__all__ = [
    "MeshSpec", "create_mesh", "best_mesh_shape", "local_device_count",
    "ShardingRules", "infer_sharding", "shard_params", "with_sharding",
    "batch_sharding", "replicated",
]
