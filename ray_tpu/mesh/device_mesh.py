"""Device mesh construction: the TPU-native substrate for every parallelism
strategy.

No reference analogue — the reference's parallelism substrate is NCCL process
groups (python/ray/util/collective/collective_group/nccl_collective_group.py);
here parallelism is expressed as named axes of a `jax.sharding.Mesh` and XLA
inserts the collectives (in-band over ICI/DCN). See SURVEY.md §2.4/§5.8.

Canonical axis order (outermost → innermost):
    dcn → pipeline → data → fsdp → expert → sequence → tensor
`tensor` is innermost so tensor-parallel collectives ride the
fastest/nearest ICI links; `dcn` is outermost so only the slowest-changing
axis crosses slices (data-parallel gradient sync tolerates DCN latency).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER: Tuple[str, ...] = (
    "dcn", "pipeline", "data", "fsdp", "expert", "sequence", "tensor")

# Aliases accepted in user configs.
_AXIS_ALIASES = {
    "dp": "data", "tp": "tensor", "pp": "pipeline", "sp": "sequence",
    "cp": "sequence", "ep": "expert", "model": "tensor",
}


def canonical_axis(name: str) -> str:
    return _AXIS_ALIASES.get(name, name)


def local_device_count(backend: Optional[str] = None) -> int:
    return len(jax.devices(backend))


@dataclasses.dataclass
class MeshSpec:
    """Declarative mesh: axis name → size. Size -1 on at most one axis means
    "use all remaining devices". ``dcn`` is the multi-slice dimension."""

    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    pipeline: int = 1
    sequence: int = 1
    expert: int = 1
    dcn: int = 1

    @classmethod
    def from_dict(cls, axes: Dict[str, int]) -> "MeshSpec":
        kwargs = {}
        for k, v in axes.items():
            ck = canonical_axis(k)
            if ck not in {f.name for f in dataclasses.fields(cls)}:
                raise ValueError(f"Unknown mesh axis {k!r}")
            kwargs[ck] = v
        return cls(**kwargs)

    def sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def resolve(self, n_devices: int) -> "MeshSpec":
        """Fill a single -1 axis so the product equals n_devices."""
        sizes = self.sizes()
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError("At most one axis may be -1")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {fixed}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"Mesh axes product {fixed} != device count {n_devices}")
        return MeshSpec(**sizes)

    def num_devices(self) -> int:
        return math.prod(self.sizes().values())

    def nontrivial_axes(self) -> List[str]:
        return [a for a in AXIS_ORDER if self.sizes()[a] > 1]


def best_mesh_shape(n_devices: int, want_data: int = -1,
                    want_tensor: int = 1) -> MeshSpec:
    """Pick a simple DP×TP mesh for n devices."""
    if n_devices % want_tensor:
        raise ValueError(
            f"tensor={want_tensor} does not divide {n_devices}")
    spec = MeshSpec(data=want_data, tensor=want_tensor)
    return spec.resolve(n_devices)


def create_mesh(spec: Optional[MeshSpec | Dict[str, int]] = None,
                devices: Optional[Sequence[jax.Device]] = None,
                allow_split_physical_axes: bool = False) -> Mesh:
    """Build a `jax.sharding.Mesh` honoring ICI topology.

    Every axis in AXIS_ORDER is present in the mesh (size-1 axes included)
    so PartitionSpecs can always name them; XLA treats size-1 axes as free.
    """
    if devices is None:
        devices = jax.devices()
    if spec is None:
        spec = MeshSpec(data=len(devices))
    if isinstance(spec, dict):
        spec = MeshSpec.from_dict(spec)
    if -1 not in spec.sizes().values() and \
            spec.num_devices() < len(devices):
        # Fully-specified smaller mesh: use a device subset.
        devices = list(devices)[:spec.num_devices()]
    spec = spec.resolve(len(devices))
    sizes = spec.sizes()
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    if spec.dcn > 1:
        # Multi-slice: split devices by slice_index (DCN tier outermost),
        # preserve ICI ordering within each slice.
        try:
            from jax.experimental import mesh_utils
            dev_array = mesh_utils.create_hybrid_device_mesh(
                shape[1:], dcn_mesh_shape=(spec.dcn,) + (1,) * 6,
                devices=devices)
            dev_array = dev_array.reshape(shape)
        except Exception:
            dev_array = np.asarray(devices).reshape(shape)
    else:
        try:
            from jax.experimental import mesh_utils
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=devices,
                allow_split_physical_axes=allow_split_physical_axes)
        except Exception:
            # CPU / virtual devices: topology doesn't matter.
            dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[canonical_axis(axis)]
