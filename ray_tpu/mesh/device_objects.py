"""Device-array (HBM) object layer: jax Arrays referenced, never copied.

The TPU-native replacement for the reference's plasma zero-copy contract
(reference: src/ray/common/ray_object.h:28 — RayObject wraps buffers
without copying; src/ray/object_manager/plasma/store.h:55 — clients map
the store's memory directly). On TPU the analogous resource is HBM, and
the analogous contract is: a `put()` of a `jax.Array` must not move the
array. It stays on device, owned by the producing process, and the
object layer hands out a small *handle* describing it:

    (object id, global shape/dtype, mesh axes, partition spec,
     per-device buffer refs)

Lifecycle, designed around XLA's ownership model rather than plasma's:

- **put**: the living Array is parked in this process's
  `DeviceObjectTable`; only a ~300-byte descriptor enters the object
  plane. No device→host transfer, no serialization of the payload.
- **same-process get**: descriptor → table hit → the *identical* Array
  object (buffer identity, asserted in tests/test_device_objects.py).
- **escape** (the ref is pickled into a task arg / actor state /
  another object): the owner spills one host copy into its shm store —
  the same escape-analysis moment the byte-object layer uses for
  memory-tier promotion (object_plane.py:promote). Until a ref
  escapes, no host copy ever exists.
- **cross-process get**: the consumer pulls the spilled host payload
  through the ordinary object plane (same-node shm / cross-node
  chunked pull) and re-materializes on its own devices with the
  handle's sharding via `jax.device_put`. Repeated gets hit a bounded
  resolved-borrow cache.
- **SPMD gang sharing**: in a multi-controller gang every process
  already holds its addressable shards of a global Array, so
  `gang_put(arr, tag)` registers the local view under a
  deterministic id on every rank and a get anywhere in the gang
  resolves to the local living Array — zero data motion, the handle
  is the only thing that ever crosses a process boundary.
- **free**: when the owner's last local ref drops, the eager-GC drain
  (object_plane._drain_releases) also drops the table entry (freeing
  HBM). A never-escaped spill is deleted on the spot; an escaped one
  rides the head's borrower protocol under `payload_oid` — consumers
  register a payload borrow at resolve, the owner's release hands the
  spill to the head, and the head frees every copy on the last
  borrow drop (grace-windowed) instead of waiting for shm LRU
  pressure.
- **reshard**: `reshard(value, axes)` moves an Array between
  shardings with `jax.device_put`, which XLA lowers to device-to-device
  copies (ICI collective permute across chips) — the host is never in
  the path.

Module-import discipline: jax is imported only inside functions, and
callers on paths that may run in jax-free processes guard with
`'ray_tpu.mesh.device_objects' in sys.modules` — a process that never
registered a device object never pays a jax import.
"""
from __future__ import annotations

import collections
import hashlib
import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import ObjectID

# Return-index sentinel marking the spilled host payload of a device
# object (real task-return indices are small ints; puts use 0).
_PAYLOAD_INDEX = (0xDE50B1).to_bytes(4, "little")

# Bounded cache of arrays this process materialized from OTHER owners'
# payloads (borrows): repeated gets of a hot ref skip the pull +
# device_put. Entries are dropped LRU beyond the budget; correctness
# never depends on a hit.
_BORROW_CACHE_BUDGET = 256 * 1024 * 1024


def payload_oid(oid: ObjectID) -> ObjectID:
    """The derived id under which a device object's host spill lives."""
    return ObjectID(oid.binary()[:-4] + _PAYLOAD_INDEX)


class DeviceArrayHandle:
    """What travels instead of the array: metadata + buffer refs.

    ``buffers`` is a tuple of (device_id, shard_index, nbytes) refs
    describing where the living HBM buffers are — the object-layer
    analogue of plasma's object header (ray_object.h:28), except the
    payload it points at is device memory owned by XLA.
    """

    __slots__ = ("oid", "shape", "dtype", "mesh_axes", "pspec",
                 "buffers", "device_kind", "fully_addressable",
                 "owner_node")

    def __init__(self, oid: bytes, shape: Tuple[int, ...], dtype: str,
                 mesh_axes: Tuple[Tuple[str, int], ...],
                 pspec: Tuple, buffers: Tuple[Tuple[int, int, int], ...],
                 device_kind: str, fully_addressable: bool,
                 owner_node: str):
        self.oid = oid
        self.shape = shape
        self.dtype = dtype
        self.mesh_axes = mesh_axes
        self.pspec = pspec
        self.buffers = buffers
        self.device_kind = device_kind
        self.fully_addressable = fully_addressable
        self.owner_node = owner_node

    def __reduce__(self):
        return (DeviceArrayHandle,
                (self.oid, self.shape, self.dtype, self.mesh_axes,
                 self.pspec, self.buffers, self.device_kind,
                 self.fully_addressable, self.owner_node))

    def __repr__(self):
        return (f"DeviceArrayHandle({ObjectID(self.oid).hex()[:12]}…, "
                f"shape={self.shape}, dtype={self.dtype}, "
                f"mesh={dict(self.mesh_axes)}, pspec={self.pspec}, "
                f"{len(self.buffers)} buffers)")


def _describe(arr) -> Tuple[Tuple[Tuple[str, int], ...], Tuple,
                            Tuple[Tuple[int, int, int], ...], str, bool]:
    """Extract (mesh_axes, pspec, buffer refs, device kind,
    fully_addressable) from a living jax Array."""
    sharding = arr.sharding
    mesh_axes: Tuple[Tuple[str, int], ...] = ()
    pspec: Tuple = ()
    try:
        mesh = sharding.mesh            # NamedSharding
        mesh_axes = tuple((str(k), int(v)) for k, v in mesh.shape.items())
        spec = sharding.spec
        pspec = tuple(
            tuple(p) if isinstance(p, (tuple, list)) else p for p in spec)
    except AttributeError:
        pass                            # SingleDeviceSharding et al.
    buffers = []
    itemsize = arr.dtype.itemsize
    for i, sh in enumerate(arr.addressable_shards):
        n = 1
        for d in sh.data.shape:
            n *= d
        buffers.append((int(sh.device.id), i, n * itemsize))
    kind = arr.devices().pop().platform if arr.devices() else "cpu"
    return (mesh_axes, pspec, tuple(buffers), kind,
            bool(arr.is_fully_addressable))


class DeviceObjectTable:
    """Per-process registry of living device Arrays keyed by ObjectID.

    The owning side of the zero-copy contract: entries hold a strong
    reference to the Array (pinning its HBM buffers) until the owner's
    last ObjectRef drops or the entry is explicitly dropped.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[ObjectID, Any] = {}
        self._planes: Dict[ObjectID, Any] = {}      # oid -> weakref(plane)
        self._spilled: set = set()
        # Main oids whose PAYLOAD this process holds a registered
        # borrow on (consumer side of the payload borrower protocol):
        # added at resolve, consumed when the main ref's release
        # drains (object_plane._device_borrow_released).
        self._payload_borrows: set = set()
        # borrow cache: oid -> (array, nbytes)
        self._borrows: "collections.OrderedDict[ObjectID, Tuple[Any, int]]" \
            = collections.OrderedDict()
        self._borrow_bytes = 0

    # ---- owner side -------------------------------------------------------

    def register(self, oid: ObjectID, arr, plane=None) -> None:
        with self._lock:
            self._entries[oid] = arr
            if plane is not None:
                self._planes[oid] = weakref.ref(plane)

    def lookup(self, oid: ObjectID):
        with self._lock:
            arr = self._entries.get(oid)
            if arr is not None:
                return arr
            hit = self._borrows.get(oid)
            if hit is not None:
                self._borrows.move_to_end(oid)
                return hit[0]
            return None

    def is_registered(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._entries

    def drop(self, oid: ObjectID) -> None:
        """Release the HBM pin (owner free path)."""
        with self._lock:
            self._entries.pop(oid, None)
            self._planes.pop(oid, None)
            self._spilled.discard(oid)
            hit = self._borrows.pop(oid, None)
            if hit is not None:
                self._borrow_bytes -= hit[1]

    def spill(self, oid: ObjectID) -> bool:
        """Write one host copy of the array into the owner plane's shm
        store under payload_oid (the escape moment — see module doc).
        Idempotent. Returns False for arrays whose shards this process
        cannot address (multi-host gang arrays resolve via gang
        registration on every rank instead — there is nothing a single
        process could spill that would reconstruct the global array).
        """
        with self._lock:
            if oid in self._spilled:
                return True
            arr = self._entries.get(oid)
            plane_ref = self._planes.get(oid)
        if arr is None or plane_ref is None:
            return False
        plane = plane_ref()
        if plane is None:
            import logging
            logging.getLogger(__name__).warning(
                "device object %s: owning plane is gone; escape spill "
                "skipped (consumers will not resolve this ref)",
                oid.hex()[:12])
            return False
        if not arr.is_fully_addressable:
            return False
        import jax
        host = jax.device_get(arr)       # the ONE device->host copy
        plane.put_obj(payload_oid(oid), ("ok", host), owned=False)
        with self._lock:
            self._spilled.add(oid)
        return True

    def was_spilled(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._spilled

    # ---- borrow side ------------------------------------------------------

    def note_payload_borrow(self, oid: ObjectID) -> None:
        with self._lock:
            self._payload_borrows.add(oid)

    def take_payload_borrow(self, oid: ObjectID) -> bool:
        """Consume the payload-borrow marker for ``oid`` (returns
        whether one existed) — called once per main-ref release."""
        with self._lock:
            if oid in self._payload_borrows:
                self._payload_borrows.discard(oid)
                return True
            return False

    def cache_borrow(self, oid: ObjectID, arr, nbytes: int) -> None:
        with self._lock:
            old = self._borrows.pop(oid, None)
            if old is not None:
                self._borrow_bytes -= old[1]
            self._borrows[oid] = (arr, nbytes)
            self._borrow_bytes += nbytes
            while self._borrow_bytes > _BORROW_CACHE_BUDGET \
                    and len(self._borrows) > 1:
                _, (_, n) = self._borrows.popitem(last=False)
                self._borrow_bytes -= n

    def stats(self) -> Dict[str, int]:
        with self._lock:
            import sys  # noqa: F401  (cheap; stats is a debug path)
            owned_bytes = 0
            for arr in self._entries.values():
                try:
                    owned_bytes += arr.nbytes
                except Exception:
                    pass
            return {"owned": len(self._entries),
                    "owned_bytes": owned_bytes,
                    "spilled": len(self._spilled),
                    "borrows": len(self._borrows),
                    "borrow_bytes": self._borrow_bytes}


_TABLE = DeviceObjectTable()


def table() -> DeviceObjectTable:
    return _TABLE


# --------------------------------------------------------------------------
# put / resolve / free hooks (called from the runtime layer)
# --------------------------------------------------------------------------

def is_device_array(value) -> bool:
    import sys
    if "jax" not in sys.modules:
        return False
    import jax
    return isinstance(value, jax.Array)


def maybe_put_device(plane, oid: ObjectID, value,
                     node_id: str = "head") -> bool:
    """put() interception: if `value` is a jax Array, park it in the
    table and store only a descriptor. Returns True if intercepted."""
    if not is_device_array(value):
        return False
    mesh_axes, pspec, buffers, kind, full = _describe(value)
    handle = DeviceArrayHandle(
        oid.binary(), tuple(int(s) for s in value.shape),
        str(value.dtype), mesh_axes, pspec, buffers, kind, full, node_id)
    _TABLE.register(oid, value, plane)
    plane.put_obj(oid, ("devobj", handle), owned=True)
    return True


def resolve_handle(handle: DeviceArrayHandle, plane,
                   timeout_ms: int = -1):
    """Turn a descriptor back into a living Array (see module doc for
    the three paths: table hit / gang-local / payload pull)."""
    oid = ObjectID(handle.oid)
    arr = _TABLE.lookup(oid)
    if arr is not None:
        return arr
    # Borrow path: pull the spilled host payload through the plane.
    # The payload is written synchronously before the descriptor can
    # escape, so an unbounded caller still gets a diagnosis instead of
    # a hang: cap the blocking wait and explain the likely cause.
    from ray_tpu._private.serialization import loads
    from ray_tpu._private.shm_store import ShmTimeout
    cap_ms = 30_000 if timeout_ms < 0 else timeout_ms
    try:
        data = plane.get_bytes(payload_oid(oid), timeout_ms=cap_ms)
    except ShmTimeout:
        if timeout_ms >= 0:
            # The caller's own deadline expired mid-pull: report it as
            # the timeout it is, not as a missing object.
            from ray_tpu.exceptions import GetTimeoutError
            raise GetTimeoutError(
                f"Get timed out pulling the host payload of device "
                f"object {oid.hex()[:12]}…") from None
        raise LookupError(
            f"device object {oid.hex()[:12]}… is not resolvable here "
            f"(no payload after {cap_ms / 1000:.0f}s): no local "
            f"registration and no host payload. Multi-host gang "
            f"arrays (fully_addressable={handle.fully_addressable}) "
            f"resolve only on gang ranks; other device objects spill "
            f"at ref escape.") from None
    status, host = loads(data)
    if status != "ok":      # pragma: no cover - spill never stores errs
        raise host
    arr = _device_put_like(host, handle)
    _TABLE.cache_borrow(oid, arr, int(getattr(host, "nbytes", 0)))
    # Payload borrower protocol: register a borrow on the PAYLOAD id
    # so the owner can free the host spill on last-borrow-drop instead
    # of leaving it to shm LRU pressure. Dropped when this process's
    # last ref to the main object releases (on_borrow_released).
    try:
        plane.note_borrow(payload_oid(oid))
        _TABLE.note_payload_borrow(oid)
    except Exception:
        pass
    return arr


def _device_put_like(host, handle: DeviceArrayHandle):
    """Re-materialize a host payload on this process's devices,
    reproducing the handle's sharding when a matching mesh fits."""
    import jax
    if handle.mesh_axes:
        sizes = dict(handle.mesh_axes)
        need = 1
        for s in sizes.values():
            need *= s
        if need <= len(jax.devices()) and need > 1:
            import numpy as np
            from jax.sharding import Mesh, NamedSharding, PartitionSpec
            try:
                try:
                    # Canonical axis names ride the ICI-aware builder.
                    from ray_tpu.mesh.device_mesh import create_mesh
                    mesh = create_mesh(sizes)
                except ValueError:
                    # Arbitrary user axis names: plain mesh, same shape.
                    devs = np.asarray(
                        jax.devices()[:need]).reshape(
                        tuple(sizes.values()))
                    mesh = Mesh(devs, tuple(sizes.keys()))
                spec = PartitionSpec(*handle.pspec)
                return jax.device_put(host, NamedSharding(mesh, spec))
            except Exception:
                pass     # device topology differs: replicate below
    return jax.device_put(host)


def spill_on_escape(oid: ObjectID) -> None:
    """Hook from ObjectRef pickling (object_ref._promote_if_local):
    an escaping ref to a device object forces the host spill so any
    other process can resolve it."""
    if _TABLE.is_registered(oid):
        _TABLE.spill(oid)


def on_ref_released(oid: ObjectID, plane, escaped: bool = False) -> None:
    """Hook from the eager-GC drain: the owner's last local ref
    dropped. Always frees the HBM pin. A never-escaped spill is
    deleted directly (no external holder can exist). An ESCAPED
    spill's lifetime is handed to the head's borrower protocol under
    ``payload_oid`` — consumers registered payload borrows at resolve
    (resolve_handle), so the head frees the host copy on the last
    borrow drop (grace-windowed for in-flight handoffs) instead of
    waiting for shm LRU pressure."""
    if not _TABLE.is_registered(oid):
        _TABLE.drop(oid)     # clears any borrow-cache entry
        return
    spilled = _TABLE.was_spilled(oid)
    _TABLE.drop(oid)
    if not spilled:
        return
    poid = payload_oid(oid)
    if escaped:
        with plane._reg_lock:
            plane._pending_owner_released.append((poid.hex(), 0.0))
        return
    try:
        plane.store.delete(poid)
    except Exception:
        pass
    if getattr(plane, "multinode", False):
        with plane._reg_lock:
            plane._pending_free.append(poid.hex())


def on_borrow_released(oid: ObjectID, plane) -> None:
    """Hook from the eager-GC drain's BORROWED branch: this process's
    last ref to a borrowed object dropped. If ``resolve_handle``
    registered a payload borrow for it, drop that borrow too — the
    owner-side protocol frees the host spill once every payload
    borrow is gone."""
    if _TABLE.take_payload_borrow(oid):
        plane.drop_borrow(payload_oid(oid))


# --------------------------------------------------------------------------
# SPMD gang sharing
# --------------------------------------------------------------------------

def gang_oid(tag: str) -> ObjectID:
    return ObjectID(
        hashlib.sha256(b"raytpu-gangobj:" + tag.encode()).digest()[:24])


def gang_put(arr, tag: str):
    """Collective put of a (possibly multi-host) global Array.

    Every gang rank calls this with its view of the same global Array;
    each registers the living Array locally under the deterministic id
    for `tag`, and rank 0 publishes the descriptor. A get anywhere in
    the gang resolves to the local living Array — the data never moves
    (on hardware, shards stay pinned in each host's HBM; only the
    handle crosses DCN).
    """
    import jax
    from ray_tpu._private.object_ref import ObjectRef
    from ray_tpu._private.worker import global_worker
    oid = gang_oid(tag)
    rt = global_worker().runtime
    plane = getattr(rt, "plane", None)
    if plane is None:           # worker facade nests the executor
        ex = getattr(rt, "_ex", None)
        plane = getattr(ex, "plane", None)
    if plane is None:
        # Local runtime: the in-process store already holds living
        # objects; register + store directly.
        _TABLE.register(oid, arr)
        rt.store.put(oid, arr)
        return ObjectRef(oid)
    _TABLE.register(oid, arr, plane)
    if jax.process_index() == 0:
        mesh_axes, pspec, buffers, kind, full = _describe(arr)
        handle = DeviceArrayHandle(
            oid.binary(), tuple(int(s) for s in arr.shape),
            str(arr.dtype), mesh_axes, pspec, buffers, kind, full,
            getattr(plane, "node_id", "head"))
        plane.put_obj(oid, ("devobj", handle), owned=False)
    return ObjectRef(oid)


def gang_drop(tag: str) -> None:
    """Release this rank's pin on a gang object."""
    _TABLE.drop(gang_oid(tag))


# --------------------------------------------------------------------------
# device-to-device resharding
# --------------------------------------------------------------------------

def reshard(value, axes: Optional[Dict[str, int]] = None, spec=None,
            mesh=None):
    """Move an Array between shardings without touching the host.

    `jax.device_put` with a NamedSharding target lowers to
    device-to-device copies — across chips this is an ICI collective
    permute; the host never sees the payload (contrast: the
    reference's GPU object transfer stages through plasma host
    memory, object_manager.h:114).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    if mesh is None:
        from ray_tpu.mesh.device_mesh import create_mesh
        mesh = create_mesh(axes or {})
    if spec is None:
        spec = PartitionSpec()
    elif not isinstance(spec, PartitionSpec):
        spec = PartitionSpec(*spec)
    return jax.device_put(value, NamedSharding(mesh, spec))
