"""Sharding-rules engine: map parameter names to PartitionSpecs.

TPU-native replacement for the reference's per-framework process-group setup
(train/torch/config.py DDP, tensorflow/config.py TF_CONFIG): instead of wiring
collectives, models declare *where each tensor lives* on the mesh and XLA
derives the collectives. Rules are (regex, PartitionSpec) pairs applied to
flattened parameter paths — composable across DP/FSDP/TP/SP/EP by naming mesh
axes.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Sequence[Tuple[str, P]]


def _flatten_with_paths(tree) -> List[Tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        parts = []
        for p in path:
            if isinstance(p, jax.tree_util.DictKey):
                parts.append(str(p.key))
            elif isinstance(p, jax.tree_util.SequenceKey):
                parts.append(str(p.idx))
            elif isinstance(p, jax.tree_util.GetAttrKey):
                parts.append(str(p.name))
            else:
                parts.append(str(p))
        out.append(("/".join(parts), leaf))
    return out


class ShardingRules:
    """Ordered (regex → PartitionSpec) rules; first match wins.

    A trailing default rule of P() (replicate) is implicit. Specs may name
    logical axes; ``axis_map`` translates logical → mesh axes (e.g.
    {"embed": None, "heads": "tensor"}).
    """

    def __init__(self, rules: Rules,
                 axis_map: Optional[Dict[str, Optional[str]]] = None):
        self._rules = [(re.compile(pat), spec) for pat, spec in rules]
        self._axis_map = axis_map or {}

    def _translate(self, spec: P) -> P:
        if not self._axis_map:
            return spec
        out = []
        for entry in spec:
            if entry is None:
                out.append(None)
            elif isinstance(entry, (tuple, list)):
                mapped = tuple(self._axis_map.get(a, a) for a in entry)
                mapped = tuple(a for a in mapped if a is not None)
                out.append(mapped if mapped else None)
            else:
                out.append(self._axis_map.get(entry, entry))
        return P(*out)

    def spec_for(self, name: str, leaf: Any) -> P:
        shape = getattr(leaf, "shape", ())
        if not shape or int(np.prod(shape)) <= 1:
            return P()  # scalars replicate
        for pat, spec in self._rules:
            if pat.search(name):
                spec = self._translate(spec)
                if len(spec) > len(shape):
                    raise ValueError(
                        f"Rule {pat.pattern!r} spec {spec} has more "
                        f"dims than param {name} shape {shape}")
                return spec
        return P()

    def tree_specs(self, tree) -> Any:
        named = dict(_flatten_with_paths(tree))
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: self.spec_for(
                "/".join(_path_str(p) for p in path), leaf), tree)


def _path_str(p) -> str:
    if isinstance(p, jax.tree_util.DictKey):
        return str(p.key)
    if isinstance(p, jax.tree_util.SequenceKey):
        return str(p.idx)
    if isinstance(p, jax.tree_util.GetAttrKey):
        return str(p.name)
    return str(p)


def infer_sharding(tree, rules: ShardingRules, mesh: Mesh):
    """Pytree of NamedShardings for `tree` under `rules`."""
    specs = rules.tree_specs(tree)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))


def shard_params(params, rules: ShardingRules, mesh: Mesh):
    """Device-put a parameter pytree according to the rules."""
    shardings = infer_sharding(params, rules, mesh)
    return jax.device_put(params, shardings)


def with_sharding(x, spec: P):
    """Sharding constraint inside jit (hint to GSPMD)."""
    return jax.lax.with_sharding_constraint(x, spec)


def batch_sharding(mesh: Mesh, *trailing: Union[str, None]) -> NamedSharding:
    """Sharding for [batch, ...] data: batch over (dcn, data, fsdp)."""
    return NamedSharding(mesh, P(("dcn", "data", "fsdp"), *trailing))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
