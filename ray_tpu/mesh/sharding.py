"""Sharding-rules engine: map parameter names to PartitionSpecs.

TPU-native replacement for the reference's per-framework process-group setup
(train/torch/config.py DDP, tensorflow/config.py TF_CONFIG): instead of wiring
collectives, models declare *where each tensor lives* on the mesh and XLA
derives the collectives. Rules are (regex, PartitionSpec) pairs applied to
flattened parameter paths — composable across DP/FSDP/TP/SP/EP by naming mesh
axes.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Sequence[Tuple[str, P]]


def _flatten_with_paths(tree) -> List[Tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        parts = []
        for p in path:
            if isinstance(p, jax.tree_util.DictKey):
                parts.append(str(p.key))
            elif isinstance(p, jax.tree_util.SequenceKey):
                parts.append(str(p.idx))
            elif isinstance(p, jax.tree_util.GetAttrKey):
                parts.append(str(p.name))
            else:
                parts.append(str(p))
        out.append(("/".join(parts), leaf))
    return out


class ShardingRules:
    """Ordered (regex → PartitionSpec) rules; first match wins.

    A trailing default rule of P() (replicate) is implicit. Specs may name
    logical axes; ``axis_map`` translates logical → mesh axes (e.g.
    {"embed": None, "heads": "tensor"}).
    """

    def __init__(self, rules: Rules,
                 axis_map: Optional[Dict[str, Optional[str]]] = None):
        self._rules = [(re.compile(pat), spec) for pat, spec in rules]
        self._axis_map = axis_map or {}

    def _translate(self, spec: P) -> P:
        if not self._axis_map:
            return spec
        out = []
        for entry in spec:
            if entry is None:
                out.append(None)
            elif isinstance(entry, (tuple, list)):
                mapped = tuple(self._axis_map.get(a, a) for a in entry)
                mapped = tuple(a for a in mapped if a is not None)
                out.append(mapped if mapped else None)
            else:
                out.append(self._axis_map.get(entry, entry))
        return P(*out)

    def spec_for(self, name: str, leaf: Any) -> P:
        shape = getattr(leaf, "shape", ())
        if not shape or int(np.prod(shape)) <= 1:
            return P()  # scalars replicate
        for pat, spec in self._rules:
            if pat.search(name):
                spec = self._translate(spec)
                if len(spec) > len(shape):
                    raise ValueError(
                        f"Rule {pat.pattern!r} spec {spec} has more "
                        f"dims than param {name} shape {shape}")
                return spec
        return P()

    def tree_specs(self, tree) -> Any:
        named = dict(_flatten_with_paths(tree))
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: self.spec_for(
                "/".join(_path_str(p) for p in path), leaf), tree)

    def matches(self, name: str, leaf: Any) -> bool:
        """True iff some rule (not the implicit replicate default)
        covers this leaf. Scalars count as matched: replicating a
        scalar is always right."""
        shape = getattr(leaf, "shape", ())
        if not shape or int(np.prod(shape)) <= 1:
            return True
        return any(pat.search(name) for pat, _ in self._rules)

    def unmatched_paths(self, tree, min_ndim: int = 2) -> List[str]:
        """Parameter paths that fell through to the implicit replicate
        default. Only leaves with ``ndim >= min_ndim`` are reported:
        1-D norm scales / biases legitimately replicate, but a matrix
        nobody wrote a rule for is almost always a sharding bug —
        silently replicated, it costs full-size HBM on every device."""
        out = []
        for name, leaf in _flatten_with_paths(tree):
            shape = getattr(leaf, "shape", ())
            if len(shape) < min_ndim:
                continue
            if not self.matches(name, leaf):
                out.append(name)
        return out


def match_partition_rules(rules: Union["ShardingRules", Rules], tree,
                          *, on_unmatched: str = "raise",
                          min_ndim: int = 2) -> Any:
    """Apply regex partition rules to a parameter pytree, refusing to
    let a large tensor silently end up replicated.

    ``rules`` is a ShardingRules or a raw ``[(regex, PartitionSpec)]``
    list. Returns a pytree of PartitionSpecs (same structure as
    ``tree``). Every leaf with ``ndim >= min_ndim`` must be covered by
    an explicit rule; uncovered paths are handled per ``on_unmatched``:

    - ``"raise"`` (default): ValueError listing every unmatched path —
      the safe mode for model weights, where an unnoticed fall-through
      to replication wastes a full weight copy per device.
    - ``"warn"``: print one warning naming the paths, then replicate.
    - ``"ignore"``: replicate silently (the pre-existing behavior).
    """
    if on_unmatched not in ("raise", "warn", "ignore"):
        raise ValueError(
            f"on_unmatched must be 'raise'|'warn'|'ignore', "
            f"got {on_unmatched!r}")
    if not isinstance(rules, ShardingRules):
        rules = ShardingRules(rules)
    if on_unmatched != "ignore":
        unmatched = rules.unmatched_paths(tree, min_ndim=min_ndim)
        if unmatched:
            msg = (f"match_partition_rules: {len(unmatched)} "
                   f"parameter(s) with ndim >= {min_ndim} matched no "
                   f"rule and would be REPLICATED on every device: "
                   + ", ".join(sorted(unmatched)))
            if on_unmatched == "raise":
                raise ValueError(msg)
            import warnings
            warnings.warn(msg, stacklevel=2)
    return rules.tree_specs(tree)


def _path_str(p) -> str:
    if isinstance(p, jax.tree_util.DictKey):
        return str(p.key)
    if isinstance(p, jax.tree_util.SequenceKey):
        return str(p.idx)
    if isinstance(p, jax.tree_util.GetAttrKey):
        return str(p.name)
    return str(p)


def infer_sharding(tree, rules: ShardingRules, mesh: Mesh):
    """Pytree of NamedShardings for `tree` under `rules`."""
    specs = rules.tree_specs(tree)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))


def shard_params(params, rules: ShardingRules, mesh: Mesh):
    """Device-put a parameter pytree according to the rules."""
    shardings = infer_sharding(params, rules, mesh)
    return jax.device_put(params, shardings)


def with_sharding(x, spec: P):
    """Sharding constraint inside jit (hint to GSPMD)."""
    return jax.lax.with_sharding_constraint(x, spec)


def batch_sharding(mesh: Mesh, *trailing: Union[str, None]) -> NamedSharding:
    """Sharding for [batch, ...] data: batch over (dcn, data, fsdp)."""
    return NamedSharding(mesh, P(("dcn", "data", "fsdp"), *trailing))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
