"""Headline benchmark: SPMD training throughput on local TPU chips.

Models (``--model``): ``gpt2`` (default, GPT-2-124M) and
``llama-1.1b`` (TinyLlama-1.1B shape — GQA + SwiGLU, the serving
family's training path). Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

vs_baseline is measured MFU / 0.40 (the north-star target from BASELINE.md:
>=40% MFU for GPT-2 on TPU; the reference has no TPU numbers to compare
against, so the target ratio is the baseline).

The measurement runs in a CHILD subprocess (``bench.py --child``) so a
wedged device-init tunnel can be killed and retried: JAX backend state is
per-process, so a fresh child is a full backend re-init. The parent makes
up to BENCH_ATTEMPTS attempts (default 4) with backoff and prints the
first successful JSON line; only if every attempt fails does it emit an
error JSON line with rc=1.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time


# Peak dense bf16 FLOP/s per chip by TPU generation.
_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "cpu": 1e11,
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu")
    for k, v in _PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return 1e11


_METRICS_BY_MODEL = {
    "gpt2": "gpt2_124m_train_tokens_per_sec_per_chip",
    "llama-1.1b": "llama_1_1b_train_tokens_per_sec_per_chip",
}


def _model_arg(argv) -> str:
    if "--model" in argv:
        name = argv[argv.index("--model") + 1]
        if name not in _METRICS_BY_MODEL:
            raise SystemExit(f"unknown --model {name!r} "
                             f"(choices: {sorted(_METRICS_BY_MODEL)})")
        return name
    return "gpt2"


def _devices_or_die(metric: str, timeout_s: float = 120.0):
    """Device init goes through the axon tunnel, which can wedge and
    block jax.devices() forever — fail FAST with a diagnosable JSON
    line instead of hanging the whole bench run."""
    import sys
    import threading
    out = {}

    def probe():
        import jax
        out["devices"] = jax.devices()

    th = threading.Thread(target=probe, daemon=True)
    th.start()
    th.join(timeout_s)
    if "devices" not in out:
        print(json.dumps({
            "metric": metric,
            "value": 0, "unit": "tokens/s/chip", "vs_baseline": 0,
            "error": f"TPU backend unreachable: jax.devices() did not "
                     f"return within {timeout_s:.0f}s (axon tunnel "
                     f"wedged?)"}))
        sys.exit(1)
    return out["devices"]


def main(model_name: str = "gpt2"):
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # env alone doesn't always override the axon plugin (smoke
        # runs); the config update must land before any device use
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.mesh import create_mesh
    from ray_tpu.train.spmd import (TrainState, make_train_step,
                                    put_batch, shard_state)

    metric = _METRICS_BY_MODEL[model_name]
    devices = _devices_or_die(metric)
    n_chips = len(devices)
    on_tpu = devices[0].platform == "tpu"

    seq = 1024
    if model_name == "llama-1.1b":
        from ray_tpu.models.llama import (Llama, LlamaConfig,
                                          llama_flops_per_token,
                                          llama_sharding_rules,
                                          llama_tiny)
        if on_tpu:
            # TinyLlama-1.1B shape: GQA (32q/4kv) + SwiGLU. remat:
            # fp32 master params + adam state already cost ~13GB of a
            # v5e's 16GB HBM, so activations must be cheap.
            cfg = LlamaConfig(vocab_size=32000, max_seq_len=seq,
                              dim=2048, n_layers=22, n_heads=32,
                              n_kv_heads=4, hidden_dim=5632,
                              remat=True)
            batch = 8 * n_chips
        else:
            cfg = llama_tiny(max_seq_len=seq)
            batch = 2
        model = Llama(cfg)
        rules = llama_sharding_rules(fsdp=on_tpu)

        def loss_fn(params, b):
            x, y = b["ids"][:, :-1], b["ids"][:, 1:]
            logits, _ = model.apply(params, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y).mean()

        fpt = llama_flops_per_token(cfg, seq)
    else:
        from ray_tpu.models import GPT2, gpt2_124m, gpt2_sharding_rules
        from ray_tpu.models.gpt2 import (flops_per_token,
                                         linear_cross_entropy)
        # Measured sweep on v5e (tools/mfu_sweep.py / mfu_round2.py):
        # batch 24 + packed flash attention (blk 1024) + lse-gather CE
        # is the per-chip sweet spot — 53.2% MFU; batch 32 regresses
        # (fp32 logits thrash HBM) and the scan-chunked fused CE loses
        # to XLA's own scheduling of the one big projection.
        batch = 24 * n_chips if on_tpu else 2
        cfg = gpt2_124m() if on_tpu else gpt2_124m(
            n_layer=2, n_embd=128, n_head=4, vocab_size=1024,
            n_ctx=seq)
        model = GPT2(cfg)
        rules = gpt2_sharding_rules(fsdp=False)

        def loss_fn(params, b):
            x, y = b["ids"][:, :-1], b["ids"][:, 1:]
            feats = model.apply(params, x, return_features=True)
            return linear_cross_entropy(feats, params["params"]["wte"],
                                        y)

        fpt = flops_per_token(cfg, seq)

    mesh = create_mesh({"data": -1}, devices=devices)

    ids = jnp.zeros((batch, seq + 1), dtype=jnp.int32)
    params = jax.jit(lambda: model.init(jax.random.PRNGKey(0),
                                        ids[:, :-1]))()
    optimizer = optax.adamw(3e-4, weight_decay=0.1)
    state = shard_state(TrainState.create(params, optimizer), rules, mesh)

    train_step = make_train_step(loss_fn, optimizer)
    rng = np.random.RandomState(0)
    data = rng.randint(0, cfg.vocab_size, size=(batch, seq + 1),
                       dtype=np.int32)

    # All shardings below are explicit NamedShardings; the ambient
    # mesh only helps newer jax pick collective layouts, and older
    # releases don't have the context manager at all.
    import contextlib
    mesh_ctx = (jax.set_mesh(mesh) if hasattr(jax, "set_mesh")
                else contextlib.nullcontext())
    with mesh_ctx:
        b = put_batch({"ids": jnp.asarray(data)}, mesh)
        # Warmup / compile. NOTE: a host fetch (float()) is the only
        # reliable execution barrier on tunneled devices —
        # block_until_ready can return before the work actually runs.
        state, metrics = train_step(state, b)
        float(metrics["loss"])

        n_steps = 30 if on_tpu else 3
        t0 = time.perf_counter()
        for _ in range(n_steps):
            state, metrics = train_step(state, b)
        final_loss = float(metrics["loss"])  # sync barrier
        dt = time.perf_counter() - t0

    tokens = batch * seq * n_steps
    tok_per_s = tokens / dt
    tok_per_s_chip = tok_per_s / n_chips
    mfu = (tok_per_s_chip * fpt) / peak_flops(devices[0])

    print(json.dumps({
        "metric": metric,
        "value": round(tok_per_s_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "mfu": round(mfu, 4),
        "chips": n_chips,
        "device": getattr(devices[0], "device_kind", "cpu"),
        "batch": batch,
        "seq": seq,
        "step_time_ms": round(1000 * dt / n_steps, 2),
        "final_loss": round(final_loss, 3),
    }))


def _error_line(msg: str, metric: str) -> str:
    return json.dumps({
        "metric": metric,
        "value": 0, "unit": "tokens/s/chip", "vs_baseline": 0,
        "error": msg})


def supervise(model_name: str = "gpt2") -> int:
    """Run the measurement in a killable child process, retrying on
    failure. Each child is a fresh OS process, so every attempt fully
    re-initializes the JAX backend (the only way to recover from a
    wedged axon tunnel short of the far end healing itself)."""
    attempts = int(os.environ.get("BENCH_ATTEMPTS", "4"))
    child_budget = float(os.environ.get("BENCH_CHILD_TIMEOUT", "900"))
    backoffs = [30.0, 60.0, 120.0]
    errors = []
    metric = _METRICS_BY_MODEL[model_name]
    child_cmd = [sys.executable, os.path.abspath(__file__), "--child",
                 "--model", model_name]
    for i in range(attempts):
        t0 = time.monotonic()
        try:
            proc = subprocess.run(
                child_cmd,
                capture_output=True, text=True, timeout=child_budget,
                cwd=os.path.dirname(os.path.abspath(__file__)))
        except subprocess.TimeoutExpired:
            errors.append(f"attempt {i + 1}: child exceeded "
                          f"{child_budget:.0f}s budget, killed")
        else:
            line = None
            for ln in (proc.stdout or "").splitlines():
                ln = ln.strip()
                if ln.startswith("{") and '"metric"' in ln:
                    line = ln
            if proc.returncode == 0 and line is not None:
                try:
                    parsed = json.loads(line)
                except json.JSONDecodeError:
                    parsed = None
                if parsed and parsed.get("value", 0) > 0:
                    print(line)
                    return 0
            tail = ((proc.stderr or "").strip().splitlines() or [""])[-1]
            detail = line or tail[:300]
            errors.append(f"attempt {i + 1} (rc={proc.returncode}, "
                          f"{time.monotonic() - t0:.0f}s): {detail}")
        sys.stderr.write(errors[-1] + "\n")
        if i < attempts - 1:
            time.sleep(backoffs[min(i, len(backoffs) - 1)])
    # Final diagnostic: prove the TRAIN PATH works by running one
    # tiny CPU step in a child (the tunnel being down is an
    # infrastructure failure, not a framework one — make that
    # distinction measurable in the artifact).
    cpu_sanity = None
    try:
        proc = subprocess.run(
            child_cmd,
            capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "BENCH_CPU_SANITY": "1"})
        for ln in (proc.stdout or "").splitlines():
            ln = ln.strip()
            if ln.startswith("{") and '"metric"' in ln:
                cpu_sanity = json.loads(ln)
    except Exception:
        pass
    out = json.loads(_error_line(
        f"all {attempts} attempts failed: "
        + " | ".join(errors)[:1200], metric))
    if cpu_sanity and cpu_sanity.get("value", 0) > 0:
        out["cpu_sanity"] = {
            "tokens_per_sec": cpu_sanity["value"],
            "final_loss": cpu_sanity.get("final_loss"),
            "note": "same train step on the CPU backend — the "
                    "framework path works; only the TPU tunnel is "
                    "unreachable"}
    print(json.dumps(out))
    return 1


if __name__ == "__main__":
    if "--child" in sys.argv:
        main(_model_arg(sys.argv))
    else:
        sys.exit(supervise(_model_arg(sys.argv)))
