"""Drive the distributed Data shuffle ops on a REAL multi-process
cluster (2 worker processes), where per-process hash randomization and
cross-process object movement actually bite. Run from /root/repo."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8"
                           ).strip()

import numpy as np

import ray_tpu
from ray_tpu.runtime.cluster_utils import Cluster


def main():
    c = Cluster(num_workers=2, resources_per_worker={"CPU": 4})
    try:

        from ray_tpu.data import from_items, range_dataset

        # 1. string-key groupby across separate worker processes
        items = [{"g": f"key-{i % 6}", "v": i} for i in range(600)]
        rows = (from_items(items, parallelism=8)
                .groupby("g").count().take_all())
        got = {r["key"]: r["count"] for r in rows}
        want = {f"key-{i}": 100 for i in range(6)}
        assert got == want, f"groupby wrong: {got}"
        print("groupby str keys across 2 worker procs: OK", got)

        # 2. distributed sample-sort, 5000 rows, 12 blocks
        rng = np.random.RandomState(7)
        vals = [int(v) for v in rng.randint(0, 10 ** 6, size=5000)]
        out = from_items(vals, parallelism=12).sort().take_all()
        assert out == sorted(vals), "sort wrong"
        print("distributed sort 5000 rows / 12 blocks: OK")

        # 3. repartition preserves order; zip aligns ranges
        ds = range_dataset(1000, parallelism=9).repartition(4)
        assert ds.take_all() == list(range(1000))
        z = (range_dataset(300, parallelism=4)
             .zip(from_items([i * 3 for i in range(300)],
                             parallelism=7)))
        assert z.take_all() == [(i, 3 * i) for i in range(300)]
        print("repartition + zip across procs: OK")

        # 4. lazy stages + shuffle in one task graph
        res = (range_dataset(400, parallelism=8)
               .map(lambda x: x % 10)
               .groupby(lambda r: r).sum(lambda r: r).take_all())
        assert {r["key"]: r["sum"] for r in res} == {
            d: d * 40 for d in range(10)}, f"lazy+groupby wrong: {res}"
        print("lazy stages -> hash shuffle -> agg: OK")

        # 5. aggregates as remote partials
        dd = from_items([{"v": i} for i in range(500)], parallelism=10)
        assert dd.sum("v") == sum(range(500))
        assert dd.min("v") == 0 and dd.max("v") == 499
        assert sorted(dd.map(lambda r: r["v"] % 13).unique()) == \
            list(range(13))
        print("sum/min/max/unique remote partials: OK")

        print("ALL DISTRIBUTED DATA CHECKS PASSED")
    finally:
        c.shutdown()


if __name__ == "__main__":
    main()
