// Native tests for the shm object store (the role of the reference's
// object-store *_test.cc suite, e.g. src/ray/object_manager/test/ —
// exercised here directly against the C API with asserts; built and
// run under ASan/UBSan and TSan by `make -C src test` / `test-tsan`).
//
// Covers: create/seal/get/release/delete lifecycle, duplicate and
// missing ids, capacity pressure + LRU eviction candidates, blocking
// get with timeout, multi-threaded producers/consumers on one
// segment, and survival of a SIGKILLed child process mid-traffic
// (robust mutex recovery).
#include <pthread.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/wait.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <thread>
#include <vector>

enum {
  SHM_OK = 0,
  SHM_ERR_EXISTS = -1,
  SHM_ERR_NOT_FOUND = -2,
  SHM_ERR_FULL = -3,
  SHM_ERR_STATE = -4,
  SHM_ERR_TIMEOUT = -5,
  SHM_ERR_SYS = -6,
  SHM_ERR_TOO_MANY = -7,
};

struct Store;
extern "C" {
Store* store_create(const char* name, uint64_t capacity);
Store* store_attach(const char* name);
void store_detach(Store* s);
void store_destroy(Store* s);
int64_t store_create_object(Store* s, const uint8_t* id, uint64_t size);
int64_t store_create_object_ex(Store* s, const uint8_t* id,
                               uint64_t size, int allow_evict);
int store_lru_candidate(Store* s, uint8_t* out_id);
int store_seal(Store* s, const uint8_t* id);
int store_get(Store* s, const uint8_t* id, int64_t timeout_ms,
              uint64_t* out_offset, uint64_t* out_size);
int store_release(Store* s, const uint8_t* id);
int store_delete(Store* s, const uint8_t* id);
int store_contains(Store* s, const uint8_t* id);
void store_stats(Store* s, uint64_t* bytes_in_use, uint64_t* num_objects,
                 uint64_t* num_evictions, uint64_t* capacity);
uint8_t* store_base(Store* s);
}

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      exit(1);                                                        \
    }                                                                 \
  } while (0)

constexpr int kIdSize = 24;   // ObjectID width (matches shm_store.cc)

static void make_id(uint8_t* id, uint64_t n) {
  memset(id, 0, kIdSize);
  memcpy(id, &n, sizeof(n));
}

static void test_lifecycle(const char* seg) {
  Store* s = store_create(seg, 1 << 20);
  CHECK(s != nullptr);
  uint8_t id[kIdSize];
  make_id(id, 1);
  int64_t off = store_create_object(s, id, 1000);
  CHECK(off >= 0);
  memset(store_base(s) + off, 0xAB, 1000);
  CHECK(store_create_object(s, id, 10) == SHM_ERR_EXISTS);
  // unsealed objects are not gettable (STATE), absent ids NOT_FOUND
  uint64_t goff = 0, gsize = 0;
  CHECK(store_get(s, id, 0, &goff, &gsize) == SHM_ERR_STATE);
  uint8_t absent[kIdSize];
  make_id(absent, 31337);
  CHECK(store_get(s, absent, 0, &goff, &gsize) == SHM_ERR_NOT_FOUND);
  CHECK(store_seal(s, id) == SHM_OK);
  CHECK(store_seal(s, id) != SHM_OK);      // double seal rejected
  CHECK(store_get(s, id, 0, &goff, &gsize) == SHM_OK);
  CHECK(gsize == 1000);
  for (int i = 0; i < 1000; i++) CHECK(store_base(s)[goff + i] == 0xAB);
  CHECK(store_contains(s, id) == 1);
  uint64_t in_use, nobj, nevict, cap;
  store_stats(s, &in_use, &nobj, &nevict, &cap);
  CHECK(nobj == 1 && in_use >= 1000 && cap == (1 << 20));
  // refcount held: delete must not free under the reader
  CHECK(store_release(s, id) == SHM_OK);
  CHECK(store_delete(s, id) == SHM_OK);
  CHECK(store_contains(s, id) == 0);
  uint8_t missing[kIdSize];
  make_id(missing, 999);
  CHECK(store_delete(s, missing) == SHM_ERR_NOT_FOUND);
  store_destroy(s);
  printf("lifecycle: OK\n");
}

static void test_capacity_and_lru(const char* seg) {
  Store* s = store_create(seg, 64 * 1024);
  CHECK(s != nullptr);
  uint8_t id[kIdSize];
  // fill with sealed, released objects
  uint64_t n = 0;
  for (;; n++) {
    make_id(id, n);
    int64_t off = store_create_object_ex(s, id, 8 * 1024, 0);
    if (off < 0) {
      CHECK(off == SHM_ERR_FULL);
      break;
    }
    CHECK(store_seal(s, id) == SHM_OK);
  }
  CHECK(n >= 6);                      // ~8 fit, minus headers
  uint8_t victim[kIdSize];
  CHECK(store_lru_candidate(s, victim) == SHM_OK);
  uint64_t first;
  memcpy(&first, victim, sizeof(first));
  CHECK(first == 0);                  // oldest seal = LRU
  // touching object 0 via get moves it off the LRU position
  uint64_t goff, gsize;
  make_id(id, 0);
  CHECK(store_get(s, id, 0, &goff, &gsize) == SHM_OK);
  CHECK(store_release(s, id) == SHM_OK);
  CHECK(store_lru_candidate(s, victim) == SHM_OK);
  memcpy(&first, victim, sizeof(first));
  CHECK(first == 1);
  // allow_evict=1 reclaims space automatically
  make_id(id, 1000);
  CHECK(store_create_object_ex(s, id, 8 * 1024, 1) >= 0);
  CHECK(store_seal(s, id) == SHM_OK);
  store_destroy(s);
  printf("capacity+lru: OK\n");
}

static void test_blocking_get(const char* seg) {
  Store* s = store_create(seg, 1 << 20);
  CHECK(s != nullptr);
  uint8_t id[kIdSize];
  make_id(id, 42);
  uint64_t goff, gsize;
  // timeout path
  CHECK(store_get(s, id, 50, &goff, &gsize) == SHM_ERR_TIMEOUT);
  std::thread producer([&] {
    usleep(100 * 1000);
    CHECK(store_create_object(s, id, 64) >= 0);
    CHECK(store_seal(s, id) == SHM_OK);
  });
  CHECK(store_get(s, id, 5000, &goff, &gsize) == SHM_OK);
  CHECK(gsize == 64);
  producer.join();
  CHECK(store_release(s, id) == SHM_OK);
  store_destroy(s);
  printf("blocking get: OK\n");
}

static void test_threaded(const char* seg) {
  Store* s = store_create(seg, 8 << 20);
  CHECK(s != nullptr);
  constexpr int kThreads = 8, kIters = 200;
  std::atomic<int> failures{0};
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; t++) {
    ts.emplace_back([&, t] {
      uint8_t id[kIdSize];
      for (int i = 0; i < kIters; i++) {
        uint64_t key = (uint64_t)t * 1000000 + i;
        make_id(id, key);
        int64_t off = store_create_object(s, id, 512);
        if (off < 0) {
          failures++;
          continue;
        }
        memset(store_base(s) + off, t + 1, 512);
        if (store_seal(s, id) != SHM_OK) failures++;
        uint64_t goff, gsize;
        if (store_get(s, id, 1000, &goff, &gsize) != SHM_OK ||
            gsize != 512 || store_base(s)[goff] != t + 1 ||
            store_base(s)[goff + 511] != t + 1) {
          failures++;
        } else {
          store_release(s, id);
        }
        if (i % 2 == 0 && store_delete(s, id) != SHM_OK) failures++;
      }
    });
  }
  for (auto& th : ts) th.join();
  CHECK(failures.load() == 0);
  uint64_t in_use, nobj, nevict, cap;
  store_stats(s, &in_use, &nobj, &nevict, &cap);
  CHECK(nobj == kThreads * kIters / 2);   // odd i survive
  store_destroy(s);
  printf("threaded producers/consumers: OK\n");
}

static void test_killed_child(const char* seg) {
  // A child hammering the store is SIGKILLed mid-traffic; the parent
  // must keep operating (robust mutex recovers an owner-died lock).
  Store* s = store_create(seg, 4 << 20);
  CHECK(s != nullptr);
  for (int round = 0; round < 3; round++) {
    pid_t pid = fork();
    if (pid == 0) {
      Store* c = store_attach(seg);
      if (!c) _exit(1);
      uint8_t id[kIdSize];
      for (uint64_t i = 0;; i++) {
        make_id(id, 500000 + (i % 64));
        int64_t off = store_create_object(c, id, 256);
        if (off >= 0) {
          store_seal(c, id);
          store_delete(c, id);
        }
      }
    }
    usleep(30 * 1000);
    kill(pid, SIGKILL);
    waitpid(pid, nullptr, 0);
    // parent traffic must continue cleanly
    uint8_t id[kIdSize];
    for (int i = 0; i < 50; i++) {
      make_id(id, 700000 + round * 100 + i);
      int64_t off = store_create_object(s, id, 128);
      CHECK(off >= 0);
      CHECK(store_seal(s, id) == SHM_OK);
      uint64_t goff, gsize;
      CHECK(store_get(s, id, 1000, &goff, &gsize) == SHM_OK);
      store_release(s, id);
      CHECK(store_delete(s, id) == SHM_OK);
    }
  }
  store_destroy(s);
  printf("SIGKILLed child recovery: OK\n");
}

int main() {
  char seg[64];
  snprintf(seg, sizeof(seg), "/shmtest_%d", (int)getpid());
  test_lifecycle(seg);
  test_capacity_and_lru(seg);
  test_blocking_get(seg);
  test_threaded(seg);
  test_killed_child(seg);
  printf("ALL STORE TESTS PASSED\n");
  return 0;
}
