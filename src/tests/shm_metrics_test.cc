// Native tests for the shm metrics registry (the role of the
// reference's stats tests, src/ray/stats/*_test.cc): counter/gauge/
// histogram semantics, cross-thread atomic accumulation, cross-process
// attach, and slot read-back. Built/run under ASan+UBSan and TSan.
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/wait.h>
#include <unistd.h>

#include <cstdint>
#include <thread>
#include <vector>

struct Registry;
extern "C" {
Registry* metrics_create(const char* name);
Registry* metrics_attach(const char* name);
void metrics_detach(Registry* r);
void metrics_destroy(Registry* r, const char* name);
int metrics_counter_add(Registry* r, const char* name, double delta);
int metrics_gauge_set(Registry* r, const char* name, double value);
int metrics_histogram_observe(Registry* r, const char* name, double v);
int metrics_num_slots(Registry* r);
int metrics_read_slot(Registry* r, int i, char* out_name,
                      double* out_value, uint64_t* out_count,
                      double* out_sum, uint64_t* out_buckets);
int metrics_name_size();
int metrics_num_buckets();
}

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      exit(1);                                                        \
    }                                                                 \
  } while (0)

static int find_slot(Registry* r, const char* want, double* value,
                     uint64_t* count, double* sum, uint64_t* buckets) {
  // caller's buckets array must hold metrics_num_buckets() entries
  int n = metrics_num_slots(r);
  std::vector<char> name(metrics_name_size() + 1);
  for (int i = 0; i < n; i++) {
    if (!metrics_read_slot(r, i, name.data(), value, count, sum,
                           buckets))
      continue;
    if (strcmp(name.data(), want) == 0) return i;
  }
  return -1;
}

int main() {
  char seg[64];
  snprintf(seg, sizeof(seg), "/shmmtest_%d", (int)getpid());
  Registry* r = metrics_create(seg);
  CHECK(r != nullptr);

  // --- concurrent counters from many threads -----------------------
  constexpr int kThreads = 8, kIters = 5000;
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; t++) {
    ts.emplace_back([&] {
      for (int i = 0; i < kIters; i++)
        CHECK(metrics_counter_add(r, "tasks_total", 1.0) == 0);
    });
  }
  for (auto& th : ts) th.join();
  double value;
  std::vector<uint64_t> bucket_store(metrics_num_buckets(), 0);
  uint64_t count;
  uint64_t* buckets = bucket_store.data();
  double sum;
  CHECK(find_slot(r, "tasks_total", &value, &count, &sum, buckets) >= 0);
  CHECK(value == (double)kThreads * kIters);
  printf("concurrent counter (%d x %d): OK\n", kThreads, kIters);

  // --- gauge last-write-wins ---------------------------------------
  CHECK(metrics_gauge_set(r, "inflight", 5.0) == 0);
  CHECK(metrics_gauge_set(r, "inflight", 2.5) == 0);
  CHECK(find_slot(r, "inflight", &value, &count, &sum, buckets) >= 0);
  CHECK(value == 2.5);
  printf("gauge: OK\n");

  // --- histogram observations --------------------------------------
  for (int i = 1; i <= 100; i++)
    CHECK(metrics_histogram_observe(r, "latency_ms", (double)i) == 0);
  CHECK(find_slot(r, "latency_ms", &value, &count, &sum, buckets) >= 0);
  CHECK(count == 100);
  CHECK(sum == 5050.0);
  uint64_t total_in_buckets = 0;
  for (int i = 0; i < metrics_num_buckets(); i++)
    total_in_buckets += buckets[i];
  CHECK(total_in_buckets == 100);
  printf("histogram: OK\n");

  // --- cross-process attach + accumulate ---------------------------
  fflush(stdout);     // don't duplicate buffered output into the child
  pid_t pid = fork();
  if (pid == 0) {
    Registry* c = metrics_attach(seg);
    if (!c) _exit(1);
    for (int i = 0; i < 1000; i++)
      metrics_counter_add(c, "tasks_total", 1.0);
    metrics_detach(c);
    _exit(0);
  }
  int status = 0;
  waitpid(pid, &status, 0);
  CHECK(WIFEXITED(status) && WEXITSTATUS(status) == 0);
  CHECK(find_slot(r, "tasks_total", &value, &count, &sum, buckets) >= 0);
  CHECK(value == (double)kThreads * kIters + 1000);
  printf("cross-process attach: OK\n");

  metrics_destroy(r, seg);
  printf("ALL METRICS TESTS PASSED\n");
  return 0;
}
