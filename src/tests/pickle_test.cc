// Round-trip + cross-version tests for the C++ pickle subset codec.
// The Python-interop direction (decode streams produced by CPython's
// protocol-5 pickler, and have CPython load ours) is exercised by
// tests/test_cpp_api.py; this binary covers the pure-C++ invariants.
#include <cstdio>
#include <cstdlib>

#include "pickle.h"

using raytpu::PickleDumps;
using raytpu::PickleLoads;
using raytpu::Value;
using raytpu::ValueDict;
using raytpu::ValueList;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      exit(1);                                                        \
    }                                                                 \
  } while (0)

static Value RoundTrip(const Value& v) {
  return PickleLoads(PickleDumps(v));
}

int main() {
  // scalars
  CHECK(RoundTrip(Value::None()).is_none());
  CHECK(RoundTrip(Value::Bool(true)).as_bool());
  CHECK(!RoundTrip(Value::Bool(false)).as_bool());
  for (int64_t i : {int64_t(0), int64_t(7), int64_t(255),
                    int64_t(256), int64_t(-1), int64_t(-123456),
                    int64_t(1) << 40, -(int64_t(1) << 40),
                    INT64_MAX, INT64_MIN})
    CHECK(RoundTrip(Value::Int(i)).as_int() == i);
  for (double d : {0.0, 1.5, -3.25e100, 1e-300})
    CHECK(RoundTrip(Value::Float(d)).as_float() == d);

  // strings / bytes incl. >255 chars and embedded NULs
  std::string lng(1000, 'x');
  CHECK(RoundTrip(Value::Str(lng)).as_str() == lng);
  std::string nul("a\0b", 3);
  CHECK(RoundTrip(Value::Bytes(nul)).as_bytes() == nul);
  CHECK(RoundTrip(Value::Str("snake🐍")).as_str() == "snake🐍");

  // containers, nested
  Value nested = Value::Dict(ValueDict{
      {Value::Str("xs"),
       Value::List({Value::Int(1), Value::Str("two"),
                    Value::Tuple({Value::Float(3.0), Value::None()})})},
      {Value::Int(7), Value::Bytes("blob")},
  });
  Value back = RoundTrip(nested);
  CHECK(back.at("xs").items().size() == 3);
  CHECK(back.at("xs").items()[2].items()[0].as_float() == 3.0);
  CHECK(back.dict()[1].second.as_bytes() == "blob");
  CHECK(RoundTrip(Value::Tuple({})).items().empty());
  CHECK(RoundTrip(Value::List({})).items().empty());
  CHECK(RoundTrip(Value::Dict({})).dict().empty());

  // unsupported opcodes must throw, not misparse
  bool threw = false;
  try {
    PickleLoads(std::string("\x80\x05\x8f.", 4));   // EMPTY_SET
  } catch (const std::exception&) {
    threw = true;
  }
  CHECK(threw);

  printf("ALL PICKLE TESTS PASSED\n");
  return 0;
}
