#include "pickle.h"

#include <algorithm>
#include <cstdio>
#include <cstring>

namespace raytpu {

// ---- Value ---------------------------------------------------------------

Value Value::Bool(bool b) {
  Value v;
  v.kind_ = Kind::kBool;
  v.b_ = b;
  return v;
}
Value Value::Int(int64_t i) {
  Value v;
  v.kind_ = Kind::kInt;
  v.i_ = i;
  return v;
}
Value Value::Float(double f) {
  Value v;
  v.kind_ = Kind::kFloat;
  v.f_ = f;
  return v;
}
Value Value::Str(std::string s) {
  Value v;
  v.kind_ = Kind::kStr;
  v.s_ = std::move(s);
  return v;
}
Value Value::Bytes(std::string b) {
  Value v;
  v.kind_ = Kind::kBytes;
  v.s_ = std::move(b);
  return v;
}
Value Value::List(ValueList items) {
  Value v;
  v.kind_ = Kind::kList;
  v.seq_ = std::make_shared<ValueList>(std::move(items));
  return v;
}
Value Value::Tuple(ValueList items) {
  Value v;
  v.kind_ = Kind::kTuple;
  v.seq_ = std::make_shared<ValueList>(std::move(items));
  return v;
}
Value Value::Dict(ValueDict items) {
  Value v;
  v.kind_ = Kind::kDict;
  v.map_ = std::make_shared<ValueDict>(std::move(items));
  return v;
}

static void TypeError(const char* want, Value::Kind got) {
  throw std::runtime_error(std::string("pickle Value: wanted ") + want +
                           ", got kind " +
                           std::to_string(static_cast<int>(got)));
}

bool Value::as_bool() const {
  if (kind_ != Kind::kBool) TypeError("bool", kind_);
  return b_;
}
int64_t Value::as_int() const {
  if (kind_ == Kind::kBool) return b_ ? 1 : 0;
  if (kind_ != Kind::kInt) TypeError("int", kind_);
  return i_;
}
double Value::as_float() const {
  if (kind_ == Kind::kInt) return static_cast<double>(i_);
  if (kind_ != Kind::kFloat) TypeError("float", kind_);
  return f_;
}
const std::string& Value::as_str() const {
  if (kind_ != Kind::kStr) TypeError("str", kind_);
  return s_;
}
const std::string& Value::as_bytes() const {
  if (kind_ != Kind::kBytes && kind_ != Kind::kStr)
    TypeError("bytes", kind_);
  return s_;
}
const ValueList& Value::items() const {
  if (kind_ != Kind::kList && kind_ != Kind::kTuple)
    TypeError("list/tuple", kind_);
  return *seq_;
}
const ValueDict& Value::dict() const {
  if (kind_ != Kind::kDict) TypeError("dict", kind_);
  return *map_;
}

const Value* Value::find(const std::string& key) const {
  for (const auto& kv : dict()) {
    if (kv.first.kind() == Kind::kStr && kv.first.as_str() == key)
      return &kv.second;
  }
  return nullptr;
}

const Value& Value::at(const std::string& key) const {
  const Value* v = find(key);
  if (!v) throw std::runtime_error("pickle dict: missing key " + key);
  return *v;
}

std::string Value::Repr() const {
  switch (kind_) {
    case Kind::kNone: return "None";
    case Kind::kBool: return b_ ? "True" : "False";
    case Kind::kInt: return std::to_string(i_);
    case Kind::kFloat: return std::to_string(f_);
    case Kind::kStr: return "'" + s_ + "'";
    case Kind::kBytes: return "b<" + std::to_string(s_.size()) + ">";
    case Kind::kList:
    case Kind::kTuple: {
      std::string out = kind_ == Kind::kList ? "[" : "(";
      for (const auto& e : *seq_) out += e.Repr() + ", ";
      return out + (kind_ == Kind::kList ? "]" : ")");
    }
    case Kind::kDict: {
      std::string out = "{";
      for (const auto& kv : *map_)
        out += kv.first.Repr() + ": " + kv.second.Repr() + ", ";
      return out + "}";
    }
  }
  return "?";
}

// ---- encoder (protocol 4) ------------------------------------------------

namespace {

void PutU32(std::string& out, uint32_t v) {
  char b[4];
  memcpy(b, &v, 4);
  out.append(b, 4);
}

void Encode(std::string& out, const Value& v) {
  switch (v.kind()) {
    case Value::Kind::kNone:
      out.push_back('N');
      break;
    case Value::Kind::kBool:
      out.push_back(v.as_bool() ? char(0x88) : char(0x89));
      break;
    case Value::Kind::kInt: {
      int64_t i = v.as_int();
      if (i >= 0 && i < 256) {
        out.push_back('K');
        out.push_back(static_cast<char>(i));
      } else if (i >= INT32_MIN && i <= INT32_MAX) {
        out.push_back('J');
        PutU32(out, static_cast<uint32_t>(static_cast<int32_t>(i)));
      } else {
        out.push_back(char(0x8a));     // LONG1
        out.push_back(8);
        char b[8];
        memcpy(b, &i, 8);
        out.append(b, 8);
      }
      break;
    }
    case Value::Kind::kFloat: {
      out.push_back('G');              // BINFLOAT: big-endian double
      double d = v.as_float();
      uint64_t bits;
      memcpy(&bits, &d, 8);
      for (int s = 56; s >= 0; s -= 8)
        out.push_back(static_cast<char>((bits >> s) & 0xff));
      break;
    }
    case Value::Kind::kStr: {
      const std::string& s = v.as_str();
      if (s.size() < 256) {
        out.push_back(char(0x8c));     // SHORT_BINUNICODE
        out.push_back(static_cast<char>(s.size()));
      } else {
        out.push_back('X');            // BINUNICODE
        PutU32(out, static_cast<uint32_t>(s.size()));
      }
      out += s;
      break;
    }
    case Value::Kind::kBytes: {
      const std::string& s = v.as_bytes();
      if (s.size() < 256) {
        out.push_back('C');            // SHORT_BINBYTES
        out.push_back(static_cast<char>(s.size()));
      } else {
        out.push_back('B');            // BINBYTES
        PutU32(out, static_cast<uint32_t>(s.size()));
      }
      out += s;
      break;
    }
    case Value::Kind::kList: {
      out.push_back(']');
      out.push_back('(');
      for (const auto& e : v.items()) Encode(out, e);
      out.push_back('e');              // APPENDS
      break;
    }
    case Value::Kind::kTuple: {
      const auto& items = v.items();
      if (items.empty()) {
        out.push_back(')');
      } else {
        out.push_back('(');
        for (const auto& e : items) Encode(out, e);
        out.push_back('t');
      }
      break;
    }
    case Value::Kind::kDict: {
      out.push_back('}');
      out.push_back('(');
      for (const auto& kv : v.dict()) {
        Encode(out, kv.first);
        Encode(out, kv.second);
      }
      out.push_back('u');              // SETITEMS
      break;
    }
  }
}

}  // namespace

std::string PickleDumps(const Value& v) {
  std::string out;
  out.push_back(char(0x80));           // PROTO
  out.push_back(4);
  Encode(out, v);
  out.push_back('.');                  // STOP
  return out;
}

// ---- decoder -------------------------------------------------------------

namespace {

class Reader {
 public:
  explicit Reader(const std::string& d) : data_(d) {}

  uint8_t U8() {
    Need(1);
    return static_cast<uint8_t>(data_[pos_++]);
  }
  uint16_t U16() {
    Need(2);
    uint16_t v;
    memcpy(&v, data_.data() + pos_, 2);
    pos_ += 2;
    return v;
  }
  uint32_t U32() {
    Need(4);
    uint32_t v;
    memcpy(&v, data_.data() + pos_, 4);
    pos_ += 4;
    return v;
  }
  uint64_t U64() {
    Need(8);
    uint64_t v;
    memcpy(&v, data_.data() + pos_, 8);
    pos_ += 8;
    return v;
  }
  std::string Take(size_t n) {
    Need(n);
    std::string s = data_.substr(pos_, n);
    pos_ += n;
    return s;
  }
  std::string Line() {
    std::string s;
    for (;;) {
      char c = static_cast<char>(U8());
      if (c == '\n') return s;
      s.push_back(c);
    }
  }
  bool AtEnd() const { return pos_ >= data_.size(); }

 private:
  void Need(size_t n) {
    if (pos_ + n > data_.size())
      throw std::runtime_error("pickle: truncated stream");
  }
  const std::string& data_;
  size_t pos_ = 0;
};

struct Mark {};     // sentinel on the unpickler stack

struct StackItem {
  bool is_mark = false;
  Value value;
};

class Unpickler {
 public:
  explicit Unpickler(const std::string& d) : r_(d) {}

  Value Run() {
    for (;;) {
      uint8_t op = r_.U8();
      switch (op) {
        case 0x80:                     // PROTO
          r_.U8();
          break;
        case 0x95:                     // FRAME
          r_.U64();
          break;
        case 'N': Push(Value::None()); break;
        case 0x88: Push(Value::Bool(true)); break;
        case 0x89: Push(Value::Bool(false)); break;
        case 'K': Push(Value::Int(r_.U8())); break;
        case 'M': Push(Value::Int(r_.U16())); break;
        case 'J':
          Push(Value::Int(static_cast<int32_t>(r_.U32())));
          break;
        case 0x8a: {                   // LONG1 (little-endian 2's cpl)
          uint8_t n = r_.U8();
          if (n > 8)
            throw std::runtime_error("pickle: LONG1 too wide");
          std::string b = r_.Take(n);
          int64_t v = 0;
          for (int i = 0; i < n; i++)
            v |= static_cast<int64_t>(static_cast<uint8_t>(b[i]))
                 << (8 * i);
          if (n > 0 && n < 8 && (b[n - 1] & 0x80))
            v -= (1LL << (8 * n));     // sign-extend
          Push(Value::Int(v));
          break;
        }
        case 'G': {                    // BINFLOAT big-endian
          uint64_t bits = 0;
          for (int i = 0; i < 8; i++) bits = (bits << 8) | r_.U8();
          double d;
          memcpy(&d, &bits, 8);
          Push(Value::Float(d));
          break;
        }
        case 0x8c: Push(Value::Str(r_.Take(r_.U8()))); break;
        case 'X': Push(Value::Str(r_.Take(r_.U32()))); break;
        case 0x8d: Push(Value::Str(r_.Take(r_.U64()))); break;
        case 'C': Push(Value::Bytes(r_.Take(r_.U8()))); break;
        case 'B': Push(Value::Bytes(r_.Take(r_.U32()))); break;
        case 0x8e: Push(Value::Bytes(r_.Take(r_.U64()))); break;
        case 0x96: {                   // BYTEARRAY8
          Push(Value::Bytes(r_.Take(r_.U64())));
          break;
        }
        case '}': Push(Value::Dict({})); break;
        case ']': Push(Value::List({})); break;
        case ')': Push(Value::Tuple({})); break;
        case '(': PushMark(); break;
        case 't': {                    // TUPLE (since mark)
          ValueList items = PopToMark();
          Push(Value::Tuple(std::move(items)));
          break;
        }
        case 0x85: {                   // TUPLE1
          Value a = Pop();
          Push(Value::Tuple({a}));
          break;
        }
        case 0x86: {
          Value b = Pop(), a = Pop();
          Push(Value::Tuple({a, b}));
          break;
        }
        case 0x87: {
          Value c = Pop(), b = Pop(), a = Pop();
          Push(Value::Tuple({a, b, c}));
          break;
        }
        case 'a': {                    // APPEND
          Value v = Pop();
          MutableList().push_back(std::move(v));
          break;
        }
        case 'e': {                    // APPENDS
          ValueList items = PopToMark();
          auto& lst = MutableList();
          for (auto& it : items) lst.push_back(std::move(it));
          break;
        }
        case 's': {                    // SETITEM
          Value v = Pop(), k = Pop();
          MutableDict().emplace_back(std::move(k), std::move(v));
          break;
        }
        case 'u': {                    // SETITEMS
          ValueList items = PopToMark();
          if (items.size() % 2 != 0)
            throw std::runtime_error("pickle: malformed SETITEMS");
          auto& d = MutableDict();
          for (size_t i = 0; i + 1 < items.size(); i += 2)
            d.emplace_back(std::move(items[i]),
                           std::move(items[i + 1]));
          break;
        }
        case 0x93: {                   // STACK_GLOBAL
          // Objects (e.g. exception instances in error replies)
          // arrive as GLOBAL + REDUCE. We cannot construct them, but
          // we CAN represent them — class path + ctor args — so error
          // paths surface real diagnostics instead of codec failures.
          Value name = Pop(), module = Pop();
          Push(Value::Str(module.as_str() + "." + name.as_str()));
          break;
        }
        case 'c': {                    // GLOBAL (newline-terminated)
          std::string module = r_.Line();
          std::string name = r_.Line();
          Push(Value::Str(module + "." + name));
          break;
        }
        case 'R':                      // REDUCE: callable(args)
        case 0x81: {                   // NEWOBJ: cls.__new__(cls,*a)
          Value args = Pop(), callable = Pop();
          Push(Value::Tuple({std::move(callable), std::move(args)}));
          break;
        }
        case 'b': {                    // BUILD: obj.__setstate__(st)
          Pop();                       // drop the state, keep the obj
          break;
        }
        case 0x94:                     // MEMOIZE
          memo_.push_back(Top());
          break;
        case 'q':                      // BINPUT
          SetMemo(r_.U8());
          break;
        case 'r':                      // LONG_BINPUT
          SetMemo(r_.U32());
          break;
        case 'h': Push(GetMemo(r_.U8())); break;      // BINGET
        case 'j': Push(GetMemo(r_.U32())); break;     // LONG_BINGET
        case '.':                      // STOP
          return Pop();
        default:
          throw std::runtime_error(
              "pickle: unsupported opcode 0x" + [op] {
                char b[8];
                snprintf(b, sizeof(b), "%02x", op);
                return std::string(b);
              }() + " (plain-data subset)");
      }
    }
  }

 private:
  void Push(Value v) {
    stack_.push_back({false, std::move(v)});
  }
  void PushMark() { stack_.push_back({true, Value()}); }
  Value Pop() {
    if (stack_.empty() || stack_.back().is_mark)
      throw std::runtime_error("pickle: stack underflow");
    Value v = std::move(stack_.back().value);
    stack_.pop_back();
    return v;
  }
  Value& Top() {
    if (stack_.empty() || stack_.back().is_mark)
      throw std::runtime_error("pickle: stack underflow");
    return stack_.back().value;
  }
  ValueList PopToMark() {
    ValueList items;
    while (!stack_.empty() && !stack_.back().is_mark) {
      items.push_back(std::move(stack_.back().value));
      stack_.pop_back();
    }
    if (stack_.empty())
      throw std::runtime_error("pickle: no mark");
    stack_.pop_back();                 // the mark
    std::reverse(items.begin(), items.end());
    return items;
  }
  // list/dict mutation in place: the container object on the stack
  // shares its payload via Value's shared_ptr, so memoized references
  // observe the mutation (python memo semantics).
  ValueList& MutableList() {
    if (stack_.empty() || stack_.back().is_mark)
      throw std::runtime_error("pickle: container op on empty stack");
    return const_cast<ValueList&>(stack_.back().value.items());
  }
  ValueDict& MutableDict() {
    if (stack_.empty() || stack_.back().is_mark)
      throw std::runtime_error("pickle: container op on empty stack");
    return const_cast<ValueDict&>(stack_.back().value.dict());
  }
  void SetMemo(size_t idx) {
    if (memo_.size() <= idx) memo_.resize(idx + 1);
    memo_[idx] = Top();
  }
  Value GetMemo(size_t idx) {
    if (idx >= memo_.size())
      throw std::runtime_error("pickle: memo miss");
    return memo_[idx];
  }

  Reader r_;
  std::vector<StackItem> stack_;
  std::vector<Value> memo_;
};

}  // namespace

Value PickleLoads(const std::string& data) {
  return Unpickler(data).Run();
}

}  // namespace raytpu
