#include "raytpu_client.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <random>
#include <stdexcept>

namespace raytpu {

// ---- shm store C API (object_store/shm_store.cc) -------------------------

extern "C" {
Store* store_attach(const char* name);
void store_detach(Store* s);
int64_t store_create_object(Store* s, const uint8_t* id, uint64_t size);
int store_seal(Store* s, const uint8_t* id);
int store_get(Store* s, const uint8_t* id, int64_t timeout_ms,
              uint64_t* out_offset, uint64_t* out_size);
int store_release(Store* s, const uint8_t* id);
uint8_t* store_base(Store* s);
}

// ---- framed authed RPC ---------------------------------------------------

class RpcConn {
 public:
  RpcConn(const std::string& addr, const std::string& token)
      : token_(token) {
    auto colon = addr.rfind(':');
    if (colon == std::string::npos)
      throw std::runtime_error("bad address " + addr);
    host_ = addr.substr(0, colon);
    port_ = std::stoi(addr.substr(colon + 1));
    Connect();
  }
  ~RpcConn() {
    if (fd_ >= 0) close(fd_);
  }

  Value Call(const std::string& method, ValueList args) {
    int64_t rid = ++rid_;
    ValueDict req{
        {Value::Str("rid"), Value::Int(rid)},
        {Value::Str("method"), Value::Str(method)},
        {Value::Str("args"), Value::Tuple(std::move(args))},
        {Value::Str("kwargs"), Value::Dict({})},
    };
    SendFrame(PickleDumps(Value::Dict(std::move(req))));
    Value reply = PickleLoads(RecvFrame());
    const Value* err = reply.find("err");
    if (err) {
      // Exception objects decode to ('module.Class', (args...))
      // representations (pickle.cc REDUCE handling), so the real
      // class and message surface here.
      throw std::runtime_error("rpc error: " + err->Repr());
    }
    return reply.at("ok");
  }

 private:
  void Connect() {
    fd_ = socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) throw std::runtime_error("socket() failed");
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons(static_cast<uint16_t>(port_));
    if (inet_pton(AF_INET, host_.c_str(), &sa.sin_addr) != 1)
      throw std::runtime_error("bad host " + host_);
    if (connect(fd_, reinterpret_cast<sockaddr*>(&sa),
                sizeof(sa)) != 0)
      throw std::runtime_error("connect to " + host_ + " failed");
    // HELLO: magic + version + token (rpc.py wire protocol v2)
    std::string hello = "RAYT";
    uint16_t version = 2, tlen = static_cast<uint16_t>(token_.size());
    hello.append(reinterpret_cast<char*>(&version), 2);
    hello.append(reinterpret_cast<char*>(&tlen), 2);
    hello += token_;
    SendAll(hello.data(), hello.size());
    // v2 handshake ACK: magic (4) + codec version (u16). A rejection
    // arrives as a length-prefixed error frame instead; its first
    // bytes are a little-endian length, never "RAYT".
    char ack[6];
    RecvAll(ack, 6);
    if (memcmp(ack, "RAYT", 4) != 0)
      throw std::runtime_error(
          "handshake rejected by server (version/auth mismatch)");
    memcpy(&peer_codec_, ack + 4, 2);
  }

  void SendAll(const char* p, size_t n) {
    while (n > 0) {
      ssize_t w = write(fd_, p, n);
      if (w <= 0) throw std::runtime_error("rpc send failed");
      p += w;
      n -= static_cast<size_t>(w);
    }
  }
  void RecvAll(char* p, size_t n) {
    while (n > 0) {
      ssize_t r = read(fd_, p, n);
      if (r <= 0) throw std::runtime_error("rpc recv failed");
      p += r;
      n -= static_cast<size_t>(r);
    }
  }
  void SendFrame(const std::string& payload) {
    uint32_t len = static_cast<uint32_t>(payload.size());
    char hdr[4];
    memcpy(hdr, &len, 4);
    SendAll(hdr, 4);
    SendAll(payload.data(), payload.size());
  }
  std::string RecvFrame() {
    char hdr[4];
    RecvAll(hdr, 4);
    uint32_t len;
    memcpy(&len, hdr, 4);
    // Peer frames are control-plane sized; a huge length means the
    // stream desynced — fail cleanly instead of a 4 GiB allocation.
    if (len > (512u << 20))
      throw std::runtime_error("rpc frame too large (desync?)");
    std::string payload(len, '\0');
    RecvAll(payload.data(), len);
    return payload;
  }

  std::string host_;
  int port_ = 0;
  std::string token_;
  int fd_ = -1;
  uint16_t peer_codec_ = 0;
  int64_t rid_ = 0;
};

// ---- ids + serialization container ---------------------------------------

namespace {

constexpr int kTaskIdLen = 20;    // ids.py _TASK_ID_LEN
constexpr int kObjectIdLen = 24;  // + 4-byte return index

std::string RandomBytes(int n) {
  static std::random_device rd;
  static std::mt19937_64 gen(rd());
  std::string out(n, '\0');
  for (int i = 0; i < n; i += 8) {
    uint64_t v = gen();
    memcpy(out.data() + i,
           &v, std::min(8, n - i));
  }
  return out;
}

std::string ToHex(const std::string& raw) {
  static const char* kHex = "0123456789abcdef";
  std::string out;
  out.reserve(raw.size() * 2);
  for (unsigned char c : raw) {
    out.push_back(kHex[c >> 4]);
    out.push_back(kHex[c & 0xf]);
  }
  return out;
}

// serialization.py dumps(): u32 nparts + int64 sizes + parts. Plain
// values never carry out-of-band buffers, so nparts == 1 both ways.
std::string ContainerDumps(const std::string& pickled) {
  std::string out;
  uint32_t nparts = 1;
  out.append(reinterpret_cast<char*>(&nparts), 4);
  int64_t size = static_cast<int64_t>(pickled.size());
  out.append(reinterpret_cast<char*>(&size), 8);
  out += pickled;
  return out;
}

std::string ContainerPart0(const uint8_t* data, uint64_t size) {
  if (size < 4) throw std::runtime_error("object container truncated");
  uint32_t nparts;
  memcpy(&nparts, data, 4);
  if (size < 4 + 8ull * nparts)
    throw std::runtime_error("object container truncated");
  int64_t part0;
  memcpy(&part0, data + 4, 8);
  uint64_t off = 4 + 8ull * nparts;
  if (size < off + static_cast<uint64_t>(part0))
    throw std::runtime_error("object container truncated");
  return std::string(reinterpret_cast<const char*>(data + off),
                     static_cast<size_t>(part0));
}

}  // namespace

std::string ObjectRef24::hex() const { return ToHex(id); }

// ---- Client --------------------------------------------------------------

Client::Client(const std::string& head_addr, const std::string& token) {
  rpc_ = new RpcConn(head_addr, token);
  store_name_ =
      rpc_->Call("cluster_info", {}).at("store_name").as_str();
  store_ = store_attach(store_name_.c_str());
  if (!store_)
    throw std::runtime_error("cannot attach shm store " + store_name_);
}

Client::~Client() {
  if (store_) store_detach(store_);
  delete rpc_;
}

void Client::KvPut(const std::string& key, const std::string& value) {
  rpc_->Call("kv_put", {Value::Str(key), Value::Bytes(value)});
}

bool Client::KvGet(const std::string& key, std::string* out) {
  Value v = rpc_->Call("kv_get", {Value::Str(key)});
  if (v.is_none()) return false;
  *out = v.as_bytes();
  return true;
}

void Client::KvDel(const std::string& key) {
  rpc_->Call("kv_del", {Value::Str(key)});
}

ObjectRef24 Client::Put(const Value& value) {
  ObjectRef24 ref{RandomBytes(kObjectIdLen)};
  // status-tuple container, exactly what Python readers expect
  std::string blob = ContainerDumps(PickleDumps(
      Value::Tuple({Value::Str("ok"), value})));
  const uint8_t* id =
      reinterpret_cast<const uint8_t*>(ref.id.data());
  int64_t off = store_create_object(store_, id, blob.size());
  if (off < 0)
    throw std::runtime_error("store_create_object failed");
  memcpy(store_base(store_) + off, blob.data(), blob.size());
  if (store_seal(store_, id) != 0)
    throw std::runtime_error("store_seal failed");
  // multinode location registration (no-op overhead on one node)
  rpc_->Call("register_objects",
             {Value::Str("head"),
              Value::List({Value::Str(ref.hex())})});
  return ref;
}

Value Client::Get(const ObjectRef24& ref, int64_t timeout_ms) {
  const uint8_t* id =
      reinterpret_cast<const uint8_t*>(ref.id.data());
  uint64_t off = 0, size = 0;
  int rc = store_get(store_, id, timeout_ms, &off, &size);
  if (rc != 0)
    throw std::runtime_error("get failed rc=" + std::to_string(rc));
  std::string part0;
  try {
    part0 = ContainerPart0(store_base(store_) + off, size);
  } catch (...) {
    store_release(store_, id);   // never leak the refcount
    throw;
  }
  store_release(store_, id);
  Value tup = PickleLoads(part0);
  const auto& items = tup.items();
  if (items.size() != 2)
    throw std::runtime_error("malformed result tuple");
  if (items[0].as_str() == "err")
    throw std::runtime_error("task failed: " + items[1].Repr());
  return items[1];
}

ObjectRef24 Client::Submit(const std::string& fn_path, ValueList args,
                           ValueDict kwargs, double num_cpus) {
  std::string task_id = RandomBytes(kTaskIdLen);
  std::string return_id = task_id + std::string("\0\0\0\0", 4);
  std::string task_hex = ToHex(task_id);
  Value resources = Value::Dict({
      {Value::Str("CPU"), Value::Float(num_cpus)}});
  Value return_ids = Value::List({Value::Bytes(return_id)});
  ValueDict spec{
      {Value::Str("task_id"), Value::Str(task_hex)},
      {Value::Str("name"), Value::Str("cpp:" + fn_path)},
      {Value::Str("fn_ref"), Value::Str("import://" + fn_path)},
      {Value::Str("args"), Value::Tuple(std::move(args))},
      {Value::Str("kwargs"), Value::Dict(std::move(kwargs))},
      {Value::Str("num_returns"), Value::Int(1)},
      {Value::Str("return_ids"), return_ids},
      {Value::Str("resources"), resources},
      {Value::Str("runtime_env"), Value::None()},
      {Value::Str("trace_ctx"), Value::None()},
  };
  // Pin to the head node: this client's data plane is the head
  // node's shm segment, so the result must be produced there. (A
  // location-directory-aware Get is the multinode follow-up.)
  Value strategy = Value::Dict({
      {Value::Str("type"), Value::Str("node_affinity")},
      {Value::Str("node_id"), Value::Str("head")},
      {Value::Str("soft"), Value::Bool(false)}});
  ValueDict meta{
      {Value::Str("task_id"), Value::Str(task_hex)},
      {Value::Str("return_ids"), return_ids},
      {Value::Str("resources"), resources},
      {Value::Str("max_retries"), Value::Int(3)},
      {Value::Str("pg_id"), Value::None()},
      {Value::Str("strategy"), strategy},
  };
  rpc_->Call("submit_task",
             {Value::Dict(std::move(meta)),
              Value::Bytes(PickleDumps(Value::Dict(std::move(spec))))});
  return ObjectRef24{std::move(return_id)};
}

Value Client::ClusterResources() {
  return rpc_->Call("cluster_resources", {});
}

}  // namespace raytpu
