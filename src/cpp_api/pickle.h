// Minimal pickle codec for the C++ user API (the protobuf-schema role
// of the reference's cross-language layer, adapted to this framework's
// pickled-dict wire protocol: src/ray/core_worker/lib — C++ API — and
// protobuf/ serve as the reference points; here C++ speaks the same
// frames the Python runtime does, restricted to PLAIN data).
//
// Encoder emits a protocol-4 stream (its string/bytes opcodes are
// protocol 3/4); decoder understands the opcode subset
// CPython/cloudpickle protocol 5 emits for plain values: None/bool/int/float/str/bytes/
// list/tuple/dict (+ FRAME/MEMOIZE/GET bookkeeping). Anything else
// (classes, closures) raises — by design: cross-language payloads are
// data, not code.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace raytpu {

class Value;
using ValueList = std::vector<Value>;
using ValueDict = std::vector<std::pair<Value, Value>>;

class Value {
 public:
  enum class Kind { kNone, kBool, kInt, kFloat, kStr, kBytes, kList,
                    kTuple, kDict };

  Value() : kind_(Kind::kNone) {}
  static Value None() { return Value(); }
  static Value Bool(bool b);
  static Value Int(int64_t v);
  static Value Float(double v);
  static Value Str(std::string s);
  static Value Bytes(std::string b);
  static Value List(ValueList items);
  static Value Tuple(ValueList items);
  static Value Dict(ValueDict items);

  Kind kind() const { return kind_; }
  bool is_none() const { return kind_ == Kind::kNone; }
  bool as_bool() const;
  int64_t as_int() const;
  double as_float() const;
  const std::string& as_str() const;
  const std::string& as_bytes() const;
  const ValueList& items() const;      // list or tuple
  const ValueDict& dict() const;

  // dict convenience: value for a string key (throws if absent)
  const Value& at(const std::string& key) const;
  const Value* find(const std::string& key) const;

  std::string Repr() const;            // debugging aid

 private:
  Kind kind_;
  bool b_ = false;
  int64_t i_ = 0;
  double f_ = 0.0;
  std::string s_;                      // str or bytes payload
  std::shared_ptr<ValueList> seq_;
  std::shared_ptr<ValueDict> map_;
};

// Serialize a Value as a pickle stream (protocol 2).
std::string PickleDumps(const Value& v);

// Parse a pickle stream (protocols 2-5, plain-data subset).
Value PickleLoads(const std::string& data);

}  // namespace raytpu
