// Interop bridge for testing the C++ pickle codec against CPython:
// reads one length-prefixed pickle stream from stdin, decodes it with
// the subset decoder, re-encodes with the subset encoder, writes the
// length-prefixed result to stdout. The pytest side pipes CPython
// protocol-5 pickles through and asserts pickle.loads(output) equals
// the original — a true cross-boundary round trip in both directions.
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

#include "pickle.h"

int main() {
  uint32_t len;
  if (fread(&len, 4, 1, stdin) != 1) return 2;
  std::string in(len, '\0');
  if (len && fread(in.data(), 1, len, stdin) != len) return 2;
  try {
    std::string out =
        raytpu::PickleDumps(raytpu::PickleLoads(in));
    uint32_t olen = static_cast<uint32_t>(out.size());
    fwrite(&olen, 4, 1, stdout);
    fwrite(out.data(), 1, out.size(), stdout);
    return 0;
  } catch (const std::exception& e) {
    fprintf(stderr, "decode failed: %s\n", e.what());
    return 1;
  }
}
