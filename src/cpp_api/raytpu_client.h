// C++ user API (reference role: the C++ worker API, src/ray/core_worker/
// lib/ + cpp/ — re-designed for this framework's architecture): a native
// client that speaks the head's authenticated framed-pickle RPC for
// control (kv, task submission, cluster state) and attaches the node's
// shm object store directly for the data plane (get/put of task results
// and objects, zero extra copies through the head).
//
// Tasks are cross-language: C++ submits an IMPORT PATH
// ("module:function") plus plain-data args; a Python worker imports and
// runs the function. Results are read back as plain data. This matches
// the reference's cross_language task model (function descriptors, not
// pickled closures).
#pragma once

#include <cstdint>
#include <string>

#include "pickle.h"

struct Store;    // from object_store/shm_store.cc

namespace raytpu {

class RpcConn;

struct ObjectRef24 {
  std::string id;      // 24 raw bytes
  std::string hex() const;
};

class Client {
 public:
  // token: the cluster secret (RAY_TPU_cluster_token); empty = unauthed
  // cluster. Connects the control plane and attaches the head node's
  // shm segment for data.
  Client(const std::string& head_addr, const std::string& token);
  ~Client();

  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  // ---- KV (GCS client parity) ----------------------------------------
  void KvPut(const std::string& key, const std::string& value);
  // returns false if the key is absent
  bool KvGet(const std::string& key, std::string* out);
  void KvDel(const std::string& key);

  // ---- objects --------------------------------------------------------
  ObjectRef24 Put(const Value& value);
  // Blocks up to timeout_ms (-1 = forever). Throws on task error.
  Value Get(const ObjectRef24& ref, int64_t timeout_ms = -1);

  // ---- tasks ----------------------------------------------------------
  // fn_path: "package.module:function". args/kwargs are plain data.
  ObjectRef24 Submit(const std::string& fn_path, ValueList args,
                     ValueDict kwargs = {}, double num_cpus = 1.0);

  // ---- cluster state --------------------------------------------------
  Value ClusterResources();

 private:
  RpcConn* rpc_ = nullptr;
  Store* store_ = nullptr;
  std::string store_name_;
};

}  // namespace raytpu
