// End-to-end drive of the C++ user API against a live cluster:
//   raytpu_cpp_demo <head_host:port>
// (cluster token read from RAY_TPU_cluster_token). Exercises KV,
// put/get through the shm data plane, cross-language task submission
// (Python executes, C++ reads the result), and error propagation.
#include <cstdio>
#include <cstdlib>
#include <stdexcept>
#include <string>

#include "raytpu_client.h"

using raytpu::Client;
using raytpu::Value;
using raytpu::ValueList;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      exit(1);                                                        \
    }                                                                 \
  } while (0)

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <head_host:port>\n", argv[0]);
    return 2;
  }
  const char* tok = getenv("RAY_TPU_cluster_token");
  Client c(argv[1], tok ? tok : "");

  // ---- KV ----------------------------------------------------------
  c.KvPut("cpp/answer", "42");
  std::string v;
  CHECK(c.KvGet("cpp/answer", &v) && v == "42");
  c.KvDel("cpp/answer");
  CHECK(!c.KvGet("cpp/answer", &v));
  printf("kv: OK\n");

  // ---- object put/get through the shm plane ------------------------
  auto ref = c.Put(Value::Dict({
      {Value::Str("xs"), Value::List({Value::Int(1), Value::Int(2),
                                      Value::Int(3)})},
      {Value::Str("tag"), Value::Str("from-c++")}}));
  Value got = c.Get(ref, 5000);
  CHECK(got.at("tag").as_str() == "from-c++");
  CHECK(got.at("xs").items().size() == 3 &&
        got.at("xs").items()[2].as_int() == 3);
  printf("put/get: OK\n");

  // ---- cross-language task: Python runs it, C++ reads it -----------
  auto r1 = c.Submit("ray_tpu.util.cross_lang:square",
                     ValueList{Value::Int(21)});
  CHECK(c.Get(r1, 30000).as_int() == 441);
  auto r2 = c.Submit("ray_tpu.util.cross_lang:describe",
                     ValueList{Value::List({Value::Float(1.5),
                                            Value::Float(2.5),
                                            Value::Float(4.0)})});
  Value stats = c.Get(r2, 30000);
  CHECK(stats.at("n").as_int() == 3);
  CHECK(stats.at("sum").as_float() == 8.0);
  printf("cross-language tasks: OK\n");

  // ---- task errors surface as C++ exceptions -----------------------
  auto r3 = c.Submit("ray_tpu.util.cross_lang:boom", ValueList{});
  bool threw = false;
  try {
    c.Get(r3, 30000);
  } catch (const std::exception& e) {
    threw = true;
  }
  CHECK(threw);
  printf("error propagation: OK\n");

  // ---- cluster state -----------------------------------------------
  Value res = c.ClusterResources();
  CHECK(res.find("CPU") != nullptr);
  printf("cluster_resources: OK (CPU=%g)\n",
         res.at("CPU").as_float());

  printf("CPP API DEMO PASSED\n");
  return 0;
}
