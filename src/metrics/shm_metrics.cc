// Shared-memory metrics core: the native stats substrate (N20).
//
// Capability parity with the reference's C++ stats core
// (src/ray/stats/metric.h DEFINE_stats registry + metric_exporter.cc
// export path): a fixed-size shared-memory segment of named metric
// slots updated with lock-free atomics by any attached process (worker
// processes record; the head aggregates by reading the segment — no
// RPC on the metrics hot path, which is the TPU-native answer to the
// reference's opencensus-to-agent pipeline).
//
// C ABI for ctypes (no pybind11 in the image). Types: counter (add),
// gauge (set), histogram (fixed exponential buckets).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x4d455452494b5301ull;  // "METRIKS\1"
constexpr int kMaxMetrics = 1024;
constexpr int kNameSize = 128;     // "name|tag1=v1,tag2=v2"
constexpr int kNumBuckets = 16;    // histogram: exponential, base 2

enum MetricType : uint32_t {
  kUnused = 0,
  kCounter = 1,
  kGauge = 2,
  kHistogram = 3,
};

struct Slot {
  char name[kNameSize];
  std::atomic<uint32_t> type;
  std::atomic<uint64_t> count;          // counter / histogram count
  std::atomic<double> value;            // gauge / counter value
  std::atomic<double> sum;              // histogram sum
  std::atomic<uint64_t> buckets[kNumBuckets];
};

struct Header {
  uint64_t magic;
  pthread_mutex_t create_mutex;   // only for slot creation
  std::atomic<uint32_t> num_slots;
  Slot slots[kMaxMetrics];
};

struct Registry {
  Header* hdr;
  size_t map_size;
};

Slot* FindSlot(Header* hdr, const char* name) {
  uint32_t n = hdr->num_slots.load(std::memory_order_acquire);
  for (uint32_t i = 0; i < n; i++) {
    if (strncmp(hdr->slots[i].name, name, kNameSize) == 0) {
      return &hdr->slots[i];
    }
  }
  return nullptr;
}

Slot* FindOrCreate(Header* hdr, const char* name, uint32_t type) {
  Slot* s = FindSlot(hdr, name);
  if (s != nullptr) return s;
  int rc = pthread_mutex_lock(&hdr->create_mutex);
  if (rc == EOWNERDEAD) {
    // A process died holding the robust mutex; mark it consistent so it
    // keeps providing mutual exclusion (else it degrades to
    // ENOTRECOVERABLE after our unlock and creation races go unlocked).
    pthread_mutex_consistent(&hdr->create_mutex);
  } else if (rc != 0) {
    return nullptr;
  }
  s = FindSlot(hdr, name);   // re-check under the lock
  if (s == nullptr) {
    uint32_t n = hdr->num_slots.load(std::memory_order_relaxed);
    if (n >= kMaxMetrics) {
      pthread_mutex_unlock(&hdr->create_mutex);
      return nullptr;
    }
    s = &hdr->slots[n];
    strncpy(s->name, name, kNameSize - 1);
    s->name[kNameSize - 1] = '\0';
    s->type.store(type, std::memory_order_relaxed);
    hdr->num_slots.store(n + 1, std::memory_order_release);
  }
  pthread_mutex_unlock(&hdr->create_mutex);
  return s;
}

void AtomicAddDouble(std::atomic<double>* target, double delta) {
  double cur = target->load(std::memory_order_relaxed);
  while (!target->compare_exchange_weak(cur, cur + delta)) {
  }
}

int BucketIndex(double v) {
  // Exponential buckets: [0,1), [1,2), [2,4), ... [2^14, inf)
  if (v < 1.0) return 0;
  int idx = 1;
  double bound = 2.0;
  while (idx < kNumBuckets - 1 && v >= bound) {
    bound *= 2.0;
    idx++;
  }
  return idx;
}

}  // namespace

extern "C" {

Registry* metrics_create(const char* name) {
  size_t map_size = sizeof(Header);
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)map_size) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* hdr = (Header*)mem;
  memset(hdr, 0, sizeof(Header));
  hdr->magic = kMagic;
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->create_mutex, &ma);
  Registry* r = new Registry{hdr, map_size};
  return r;
}

Registry* metrics_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t map_size = sizeof(Header);
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* hdr = (Header*)mem;
  if (hdr->magic != kMagic) {
    munmap(mem, map_size);
    return nullptr;
  }
  return new Registry{hdr, map_size};
}

void metrics_detach(Registry* r) {
  if (r == nullptr) return;
  munmap(r->hdr, r->map_size);
  delete r;
}

void metrics_destroy(Registry* r, const char* name) {
  if (r == nullptr) return;
  munmap(r->hdr, r->map_size);
  shm_unlink(name);
  delete r;
}

// type: 1=counter 2=gauge 3=histogram. Returns 0 ok, -1 full.
int metrics_counter_add(Registry* r, const char* name, double delta) {
  Slot* s = FindOrCreate(r->hdr, name, kCounter);
  if (s == nullptr) return -1;
  AtomicAddDouble(&s->value, delta);
  s->count.fetch_add(1, std::memory_order_relaxed);
  return 0;
}

int metrics_gauge_set(Registry* r, const char* name, double value) {
  Slot* s = FindOrCreate(r->hdr, name, kGauge);
  if (s == nullptr) return -1;
  s->value.store(value, std::memory_order_relaxed);
  return 0;
}

int metrics_histogram_observe(Registry* r, const char* name, double v) {
  Slot* s = FindOrCreate(r->hdr, name, kHistogram);
  if (s == nullptr) return -1;
  AtomicAddDouble(&s->sum, v);
  s->count.fetch_add(1, std::memory_order_relaxed);
  s->buckets[BucketIndex(v)].fetch_add(1, std::memory_order_relaxed);
  return 0;
}

int metrics_num_slots(Registry* r) {
  return (int)r->hdr->num_slots.load(std::memory_order_acquire);
}

// Read slot i into out params. Returns type, or 0 if out of range.
int metrics_read_slot(Registry* r, int i, char* out_name,
                      double* out_value, uint64_t* out_count,
                      double* out_sum, uint64_t* out_buckets) {
  uint32_t n = r->hdr->num_slots.load(std::memory_order_acquire);
  if (i < 0 || (uint32_t)i >= n) return 0;
  Slot* s = &r->hdr->slots[i];
  strncpy(out_name, s->name, kNameSize);
  *out_value = s->value.load(std::memory_order_relaxed);
  *out_count = s->count.load(std::memory_order_relaxed);
  *out_sum = s->sum.load(std::memory_order_relaxed);
  for (int b = 0; b < kNumBuckets; b++) {
    out_buckets[b] = s->buckets[b].load(std::memory_order_relaxed);
  }
  return (int)s->type.load(std::memory_order_relaxed);
}

int metrics_name_size() { return kNameSize; }
int metrics_num_buckets() { return kNumBuckets; }

}  // extern "C"
