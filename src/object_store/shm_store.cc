// Shared-memory immutable object store (plasma-equivalent).
//
// Capability parity with the reference's plasma store
// (src/ray/object_manager/plasma/store.h, dlmalloc.cc, eviction_policy.cc):
// a shm arena shared by every process on the node, immutable objects with
// create/seal/get lifecycle, per-object reference counts, LRU eviction of
// unreferenced sealed objects under pressure, blocking get with deadline.
// Design differences (TPU-native runtime): the arena lives in ONE
// mmap'd /dev/shm segment with an embedded header (hash table + free list +
// process-shared mutex/condvar), so attach is a single mmap and there is no
// store daemon process — the raylet-equivalent owns lifecycle, clients
// attach read-write. Device (HBM) arrays are NOT stored here; they are
// referenced by handle (see ray_tpu/mesh docs) — this store is the host-RAM
// tier only.
//
// Build: g++ -O2 -shared -fPIC -o libshm_store.so shm_store.cc -lpthread -lrt

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x52544f54;  // "TOTR" (v2 layout)
constexpr int kIdSize = 24;              // ObjectID width (ids.py)
constexpr uint32_t kMaxObjects = 65536;
constexpr uint32_t kNumBuckets = 32768;  // hash buckets (power of 2)
constexpr uint32_t kInvalid = 0xffffffffu;

enum ObjectState : uint32_t {
  kFree = 0,
  kCreated = 1,   // allocated, writer filling it
  kSealed = 2,    // immutable, readable
};

struct Entry {
  uint8_t id[kIdSize];
  uint64_t offset;        // data offset from arena base
  uint64_t size;
  uint32_t state;
  int32_t refcount;
  uint64_t seal_seq;      // for LRU (monotonic seal/get counter)
  uint32_t next;          // next entry index in bucket chain
  uint32_t in_use;
};

struct FreeBlock {
  uint64_t offset;
  uint64_t size;
};

struct Header {
  uint32_t magic;
  uint32_t initialized;
  uint64_t capacity;          // data-region capacity
  uint64_t data_start;        // offset of data region from map base
  pthread_mutex_t mutex;
  pthread_cond_t cond;
  uint64_t seq;               // LRU clock
  uint64_t bytes_in_use;
  uint64_t num_objects;
  uint64_t num_evictions;
  uint32_t free_entry_head;   // O(1) entry allocation (chained via
                              // Entry.next, which is otherwise only
                              // used for in_use bucket chains)
  uint32_t buckets[kNumBuckets];
  Entry entries[kMaxObjects];
  uint32_t free_count;
  FreeBlock free_list[kMaxObjects + 1];
};

struct Store {
  Header* hdr;
  uint8_t* base;      // mmap base
  size_t map_size;
  char name[256];
};

uint32_t HashId(const uint8_t* id) {
  // FNV-1a over the id bytes.
  uint32_t h = 2166136261u;
  for (int i = 0; i < kIdSize; i++) {
    h ^= id[i];
    h *= 16777619u;
  }
  return h & (kNumBuckets - 1);
}

Entry* FindLocked(Header* hdr, const uint8_t* id, uint32_t* out_index) {
  uint32_t b = HashId(id);
  uint32_t idx = hdr->buckets[b];
  while (idx != kInvalid) {
    Entry* e = &hdr->entries[idx];
    if (e->in_use && memcmp(e->id, id, kIdSize) == 0) {
      if (out_index) *out_index = idx;
      return e;
    }
    idx = e->next;
  }
  return nullptr;
}

void UnlinkLocked(Header* hdr, uint32_t index) {
  Entry* e = &hdr->entries[index];
  uint32_t b = HashId(e->id);
  uint32_t idx = hdr->buckets[b];
  uint32_t prev = kInvalid;
  while (idx != kInvalid) {
    if (idx == index) {
      if (prev == kInvalid)
        hdr->buckets[b] = e->next;
      else
        hdr->entries[prev].next = e->next;
      break;
    }
    prev = idx;
    idx = hdr->entries[idx].next;
  }
  e->in_use = 0;
  e->state = kFree;
  e->next = hdr->free_entry_head;     // back onto the entry free list
  hdr->free_entry_head = index;
}

// --- free-list allocator (first fit, address-ordered coalescing) ---------

void FreeInsertLocked(Header* hdr, uint64_t offset, uint64_t size) {
  // Insert keeping address order, then coalesce neighbors.
  uint32_t n = hdr->free_count;
  uint32_t pos = 0;
  while (pos < n && hdr->free_list[pos].offset < offset) pos++;
  for (uint32_t i = n; i > pos; i--) hdr->free_list[i] = hdr->free_list[i - 1];
  hdr->free_list[pos] = {offset, size};
  hdr->free_count++;
  // Coalesce with next.
  if (pos + 1 < hdr->free_count &&
      hdr->free_list[pos].offset + hdr->free_list[pos].size ==
          hdr->free_list[pos + 1].offset) {
    hdr->free_list[pos].size += hdr->free_list[pos + 1].size;
    for (uint32_t i = pos + 1; i + 1 < hdr->free_count; i++)
      hdr->free_list[i] = hdr->free_list[i + 1];
    hdr->free_count--;
  }
  // Coalesce with prev.
  if (pos > 0 && hdr->free_list[pos - 1].offset +
                     hdr->free_list[pos - 1].size ==
                 hdr->free_list[pos].offset) {
    hdr->free_list[pos - 1].size += hdr->free_list[pos].size;
    for (uint32_t i = pos; i + 1 < hdr->free_count; i++)
      hdr->free_list[i] = hdr->free_list[i + 1];
    hdr->free_count--;
  }
}

bool AllocLocked(Header* hdr, uint64_t size, uint64_t* out_offset) {
  for (uint32_t i = 0; i < hdr->free_count; i++) {
    if (hdr->free_list[i].size >= size) {
      *out_offset = hdr->free_list[i].offset;
      hdr->free_list[i].offset += size;
      hdr->free_list[i].size -= size;
      if (hdr->free_list[i].size == 0) {
        for (uint32_t j = i; j + 1 < hdr->free_count; j++)
          hdr->free_list[j] = hdr->free_list[j + 1];
        hdr->free_count--;
      }
      return true;
    }
  }
  return false;
}

// Evict the least-recently-sealed/gotten object with refcount==0.
// Returns false if nothing evictable.
bool EvictOneLocked(Header* hdr) {
  uint32_t victim = kInvalid;
  uint64_t best_seq = ~0ull;
  for (uint32_t i = 0; i < kMaxObjects; i++) {
    Entry* e = &hdr->entries[i];
    if (e->in_use && e->state == kSealed && e->refcount == 0 &&
        e->seal_seq < best_seq) {
      best_seq = e->seal_seq;
      victim = i;
    }
  }
  if (victim == kInvalid) return false;
  Entry* e = &hdr->entries[victim];
  uint64_t asize = ((e->size ? e->size : 1) + 63) & ~63ull;
  hdr->bytes_in_use -= asize;
  hdr->num_objects--;
  hdr->num_evictions++;
  FreeInsertLocked(hdr, e->offset, asize);
  UnlinkLocked(hdr, victim);
  return true;
}

uint64_t Align(uint64_t v) { return (v + 63) & ~63ull; }

}  // namespace

extern "C" {

// Error codes.
enum {
  SHM_OK = 0,
  SHM_ERR_EXISTS = -1,
  SHM_ERR_NOT_FOUND = -2,
  SHM_ERR_FULL = -3,
  SHM_ERR_STATE = -4,
  SHM_ERR_TIMEOUT = -5,
  SHM_ERR_SYS = -6,
  SHM_ERR_TOO_MANY = -7,
};

Store* store_create(const char* name, uint64_t capacity) {
  size_t map_size = sizeof(Header) + capacity;
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)map_size) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* hdr = (Header*)mem;
  memset(hdr, 0, sizeof(Header));
  hdr->magic = kMagic;
  hdr->capacity = capacity;
  hdr->data_start = sizeof(Header);
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mutex, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&hdr->cond, &ca);
  for (uint32_t i = 0; i < kNumBuckets; i++) hdr->buckets[i] = kInvalid;
  for (uint32_t i = 0; i < kMaxObjects; i++)
    hdr->entries[i].next = (i + 1 < kMaxObjects) ? i + 1 : kInvalid;
  hdr->free_entry_head = 0;
  hdr->free_count = 1;
  hdr->free_list[0] = {0, capacity};
  hdr->initialized = 1;
  Store* s = new Store();
  s->hdr = hdr;
  s->base = (uint8_t*)mem;
  s->map_size = map_size;
  strncpy(s->name, name, sizeof(s->name) - 1);
  return s;
}

Store* store_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* hdr = (Header*)mem;
  if (hdr->magic != kMagic || !hdr->initialized) {
    munmap(mem, (size_t)st.st_size);
    return nullptr;
  }
  Store* s = new Store();
  s->hdr = hdr;
  s->base = (uint8_t*)mem;
  s->map_size = (size_t)st.st_size;
  strncpy(s->name, name, sizeof(s->name) - 1);
  return s;
}

void store_detach(Store* s) {
  if (!s) return;
  munmap(s->base, s->map_size);
  delete s;
}

void store_destroy(Store* s) {
  if (!s) return;
  char name[256];
  strncpy(name, s->name, sizeof(name));
  store_detach(s);
  shm_unlink(name);
}

static int Lock(Header* hdr) {
  int rc = pthread_mutex_lock(&hdr->mutex);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&hdr->mutex);
    return 0;
  }
  return rc;
}

// Allocates an object; returns its data pointer (into shm) or error.
// allow_evict=0 returns SHM_ERR_FULL without evicting anything, so a
// spilling layer above can keep primary copies durable (the analogue of
// plasma only evicting objects that were spilled or are reconstructable).
int64_t store_create_object_ex(Store* s, const uint8_t* id, uint64_t size,
                               int allow_evict) {
  Header* hdr = s->hdr;
  uint64_t asize = Align(size ? size : 1);
  if (Lock(hdr) != 0) return SHM_ERR_SYS;
  if (FindLocked(hdr, id, nullptr)) {
    pthread_mutex_unlock(&hdr->mutex);
    return SHM_ERR_EXISTS;
  }
  if (hdr->free_entry_head == kInvalid) {
    // Entry table exhausted: evicting one sealed object frees a slot.
    if (!allow_evict || !EvictOneLocked(hdr)) {
      pthread_mutex_unlock(&hdr->mutex);
      return SHM_ERR_TOO_MANY;
    }
  }
  uint64_t offset;
  while (!AllocLocked(hdr, asize, &offset)) {
    if (!allow_evict || !EvictOneLocked(hdr)) {
      pthread_mutex_unlock(&hdr->mutex);
      return SHM_ERR_FULL;
    }
  }
  // Pop the entry slot only after space is secured — the FULL path
  // above must not leak slots.
  uint32_t slot = hdr->free_entry_head;   // O(1) entry allocation
  hdr->free_entry_head = hdr->entries[slot].next;
  Entry* e = &hdr->entries[slot];
  memcpy(e->id, id, kIdSize);
  e->offset = offset;
  e->size = size;
  e->state = kCreated;
  e->refcount = 1;  // creator holds a ref until seal+release
  e->seal_seq = 0;
  uint32_t b = HashId(id);
  e->next = hdr->buckets[b];
  hdr->buckets[b] = slot;
  e->in_use = 1;
  hdr->bytes_in_use += asize;
  hdr->num_objects++;
  pthread_mutex_unlock(&hdr->mutex);
  return (int64_t)(hdr->data_start + offset);
}

int64_t store_create_object(Store* s, const uint8_t* id, uint64_t size) {
  return store_create_object_ex(s, id, size, 1);
}

// Copy the id of the least-recently-used sealed refcount-0 object into
// out_id. Lets a spilling layer pick the eviction victim, move it to
// disk, then delete it — spill-before-evict (plasma eviction_policy.cc
// analogue where only spilled objects become evictable).
int store_lru_candidate(Store* s, uint8_t* out_id) {
  Header* hdr = s->hdr;
  if (Lock(hdr) != 0) return SHM_ERR_SYS;
  uint32_t victim = kInvalid;
  uint64_t best_seq = ~0ull;
  for (uint32_t i = 0; i < kMaxObjects; i++) {
    Entry* e = &hdr->entries[i];
    if (e->in_use && e->state == kSealed && e->refcount == 0 &&
        e->seal_seq < best_seq) {
      best_seq = e->seal_seq;
      victim = i;
    }
  }
  if (victim == kInvalid) {
    pthread_mutex_unlock(&hdr->mutex);
    return SHM_ERR_NOT_FOUND;
  }
  memcpy(out_id, hdr->entries[victim].id, kIdSize);
  pthread_mutex_unlock(&hdr->mutex);
  return SHM_OK;
}

int store_seal(Store* s, const uint8_t* id) {
  Header* hdr = s->hdr;
  if (Lock(hdr) != 0) return SHM_ERR_SYS;
  Entry* e = FindLocked(hdr, id, nullptr);
  if (!e) {
    pthread_mutex_unlock(&hdr->mutex);
    return SHM_ERR_NOT_FOUND;
  }
  if (e->state != kCreated) {
    pthread_mutex_unlock(&hdr->mutex);
    return SHM_ERR_STATE;
  }
  e->state = kSealed;
  e->refcount -= 1;  // drop the creator ref
  e->seal_seq = ++hdr->seq;
  pthread_cond_broadcast(&hdr->cond);
  pthread_mutex_unlock(&hdr->mutex);
  return SHM_OK;
}

// Blocking get: waits for seal up to timeout_ms (-1 = forever, 0 = poll).
// On success fills offset/size and bumps refcount (caller must release).
int store_get(Store* s, const uint8_t* id, int64_t timeout_ms,
              uint64_t* out_offset, uint64_t* out_size) {
  Header* hdr = s->hdr;
  if (Lock(hdr) != 0) return SHM_ERR_SYS;
  struct timespec deadline;
  if (timeout_ms > 0) {
    clock_gettime(CLOCK_REALTIME, &deadline);
    deadline.tv_sec += timeout_ms / 1000;
    deadline.tv_nsec += (timeout_ms % 1000) * 1000000L;
    if (deadline.tv_nsec >= 1000000000L) {
      deadline.tv_sec += 1;
      deadline.tv_nsec -= 1000000000L;
    }
  }
  for (;;) {
    Entry* e = FindLocked(hdr, id, nullptr);
    if (e && e->state == kSealed) {
      e->refcount++;
      e->seal_seq = ++hdr->seq;  // LRU touch
      *out_offset = hdr->data_start + e->offset;
      *out_size = e->size;
      pthread_mutex_unlock(&hdr->mutex);
      return SHM_OK;
    }
    if (timeout_ms == 0) {
      pthread_mutex_unlock(&hdr->mutex);
      return e ? SHM_ERR_STATE : SHM_ERR_NOT_FOUND;
    }
    int rc;
    if (timeout_ms < 0) {
      rc = pthread_cond_wait(&hdr->cond, &hdr->mutex);
    } else {
      rc = pthread_cond_timedwait(&hdr->cond, &hdr->mutex, &deadline);
    }
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&hdr->mutex);
      return SHM_ERR_TIMEOUT;
    }
    if (rc != 0 && rc != EOWNERDEAD) {
      pthread_mutex_unlock(&hdr->mutex);
      return SHM_ERR_SYS;
    }
  }
}

int store_release(Store* s, const uint8_t* id) {
  Header* hdr = s->hdr;
  if (Lock(hdr) != 0) return SHM_ERR_SYS;
  Entry* e = FindLocked(hdr, id, nullptr);
  if (!e) {
    pthread_mutex_unlock(&hdr->mutex);
    return SHM_ERR_NOT_FOUND;
  }
  if (e->refcount > 0) e->refcount--;
  pthread_mutex_unlock(&hdr->mutex);
  return SHM_OK;
}

int store_delete(Store* s, const uint8_t* id) {
  Header* hdr = s->hdr;
  if (Lock(hdr) != 0) return SHM_ERR_SYS;
  uint32_t idx;
  Entry* e = FindLocked(hdr, id, &idx);
  if (!e) {
    pthread_mutex_unlock(&hdr->mutex);
    return SHM_ERR_NOT_FOUND;
  }
  if (e->refcount > 0) {
    pthread_mutex_unlock(&hdr->mutex);
    return SHM_ERR_STATE;
  }
  hdr->bytes_in_use -= Align(e->size ? e->size : 1);
  hdr->num_objects--;
  FreeInsertLocked(hdr, e->offset, Align(e->size ? e->size : 1));
  UnlinkLocked(hdr, idx);
  pthread_mutex_unlock(&hdr->mutex);
  return SHM_OK;
}

int store_contains(Store* s, const uint8_t* id) {
  Header* hdr = s->hdr;
  if (Lock(hdr) != 0) return 0;
  Entry* e = FindLocked(hdr, id, nullptr);
  int sealed = (e && e->state == kSealed) ? 1 : 0;
  pthread_mutex_unlock(&hdr->mutex);
  return sealed;
}

void store_stats(Store* s, uint64_t* bytes_in_use, uint64_t* num_objects,
                 uint64_t* num_evictions, uint64_t* capacity) {
  Header* hdr = s->hdr;
  Lock(hdr);
  *bytes_in_use = hdr->bytes_in_use;
  *num_objects = hdr->num_objects;
  *num_evictions = hdr->num_evictions;
  *capacity = hdr->capacity;
  pthread_mutex_unlock(&hdr->mutex);
}

uint8_t* store_base(Store* s) { return s->base; }

}  // extern "C"
